"""Per-pod usage distributions: the vocabulary behind capacity-at-risk.

Point requests are fiction in production — a pod's *request* is a
planning number, its *usage* a random variable.  This module gives that
variable a small, validated vocabulary (the chance-constrained framing
of "Solving the Batch Stochastic Bin Packing Problem in Cloud",
PAPERS.md):

* ``point``     — the degenerate distribution (the classic fixed request);
* ``normal``    — ``round(mean + std·Z)``, clamped to the sane usage
  domain ``[1, 2^62]`` (a usage sample must be a valid kernel divisor);
* ``lognormal`` — ``round(exp(ln(mean) + sigma·Z))``, the heavy-tailed
  shape real CPU usage exhibits, same clamp;
* ``empirical`` — an explicit value/weight histogram, e.g. extracted
  from the audit log's recorded generations (:mod:`.history`).

Specs load through the same watchlist-style YAML/JSON grammar as every
other operator file, with quantity strings parsed by the reference
codecs (``500m`` CPU, ``1gb`` memory) so a distribution's mean is the
same number the flag surface would produce.

Sampling is **deterministic and counter-based**: every draw comes from
``jax.random`` (threefry — a counter-based PRNG) keyed by an explicit
integer seed, never wall-clock state, so a run is replayable bit-for-bit
— the numpy oracle re-draws the identical samples from the identical
seed.  The draw kernels are jit-pure (array math only; no registry, no
locks, no I/O — enforced by kccap-lint's jit-purity prover); everything
host-side (validation, parsing, the quantile reduction in :mod:`.car`)
stays out of traced code.
"""

from __future__ import annotations

import json
import math
import os
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from kubernetesclustercapacity_tpu.utils.quantity import (
    QuantityParseError,
    cpu_parse_error_payload,
    cpu_to_milli_reference,
    to_bytes_reference,
)

__all__ = [
    "DIST_KINDS",
    "DistributionError",
    "MAX_USAGE",
    "StochasticSpec",
    "UsageDistribution",
    "default_samples",
    "load_stochastic_spec",
    "parse_distribution",
    "parse_stochastic_spec",
    "sample_key",
    "sample_usage",
]

DIST_KINDS = ("point", "normal", "lognormal", "empirical")

#: Usage samples live in ``[1, MAX_USAGE]``: 0 would divide-by-zero the
#: reference kernel (SURVEY.md §2.4 Q8) and anything past 2^62 pushes
#: the int64 carrier into wrap territory — not a usage observation.
MAX_USAGE = 1 << 62

#: Default Monte Carlo sample count when a spec does not pin one
#: (``KCCAP_CAR_SAMPLES`` overrides process-wide).
DEFAULT_SAMPLES = 64

_MAX_SAMPLES = 1 << 16


class DistributionError(ValueError):
    """Malformed usage-distribution spec (bad kind, bad quantity, bad
    weights) — the watchlist-grammar analog of ``WatchError``."""


def default_samples() -> int:
    """The process default sample count (``KCCAP_CAR_SAMPLES``, else 64).

    Read per evaluation (host-side only — never inside jitted code) so
    the escape hatch works without a restart; junk values fall back to
    the built-in default rather than failing an evaluation.
    """
    try:
        env = int(os.environ.get("KCCAP_CAR_SAMPLES", "0"))
    except ValueError:
        env = 0
    return env if 2 <= env <= _MAX_SAMPLES else DEFAULT_SAMPLES


@dataclass(frozen=True)
class UsageDistribution:
    """One resource's per-pod usage distribution (validated, immutable).

    Only the fields of the active ``kind`` are meaningful; units are
    the kernel's native integers (millicores / bytes).
    """

    kind: str
    value: int = 0  # point
    mean: float = 0.0  # normal / lognormal (native units)
    std: float = 0.0  # normal
    sigma: float = 0.0  # lognormal (log-space std)
    values: tuple[int, ...] = ()  # empirical
    weights: tuple[float, ...] = ()  # empirical (same length as values)

    @property
    def degenerate(self) -> bool:
        """True when every sample is the same value — a point request in
        disguise, for which every capacity quantile equals the plain fit."""
        if self.kind == "point":
            return True
        if self.kind == "normal":
            return self.std == 0.0
        if self.kind == "lognormal":
            return self.sigma == 0.0
        return len(set(self.values)) <= 1

    def to_wire(self) -> dict:
        """JSON-able description (rides watch/op wire shapes)."""
        out: dict = {"dist": self.kind}
        if self.kind == "point":
            out["value"] = self.value
        elif self.kind == "normal":
            out.update(mean=self.mean, std=self.std)
        elif self.kind == "lognormal":
            out.update(mean=self.mean, sigma=self.sigma)
        else:
            out.update(values=list(self.values), weights=list(self.weights))
        return out


@dataclass(frozen=True)
class StochasticSpec:
    """A full capacity-at-risk question: usage distributions + target.

    ``samples=0`` means "the process default" (:func:`default_samples`),
    resolved at evaluation time; ``confidence`` is the schedulability
    bar ``kccap -car-spec`` exits by (``P(fit) >= confidence``).
    """

    cpu: UsageDistribution
    memory: UsageDistribution
    replicas: int = 1
    samples: int = 0
    seed: int = 0
    confidence: float = 0.95

    def n_samples(self) -> int:
        return self.samples if self.samples else default_samples()

    def to_wire(self) -> dict:
        return {
            "usage": {"cpu": self.cpu.to_wire(), "memory": self.memory.to_wire()},
            "replicas": self.replicas,
            "samples": self.n_samples(),
            "seed": self.seed,
            "confidence": self.confidence,
        }


# -- grammar ---------------------------------------------------------------

def _quantity(resource: str, v, *, field: str) -> int:
    """One quantity: a string through the reference codecs (``500m`` /
    ``1gb``) or a plain number in native units (millicores / bytes)."""
    if isinstance(v, bool):
        raise DistributionError(f"{field}: expected a quantity, got {v!r}")
    if isinstance(v, (int, float)):
        if isinstance(v, float) and not v.is_integer():
            raise DistributionError(
                f"{field}: native-unit quantities must be integers, got {v!r}"
            )
        return int(v)
    if not isinstance(v, str):
        raise DistributionError(f"{field}: expected a quantity, got {v!r}")
    if resource == "cpu":
        # The reference codec zeroes unparseable values (printing a
        # payload); a distribution parameter must fail loudly instead.
        if cpu_parse_error_payload(v) is not None:
            raise DistributionError(f"{field}: bad cpu quantity {v!r}")
        return cpu_to_milli_reference(v)
    try:
        return to_bytes_reference(v)
    except QuantityParseError as e:
        raise DistributionError(f"{field}: bad memory quantity {v!r}: {e}") from e


def _usage_value(resource: str, v, *, field: str) -> int:
    q = _quantity(resource, v, field=field)
    if not 1 <= q <= MAX_USAGE:
        raise DistributionError(
            f"{field}: usage must be in [1, 2^62], got {q}"
        )
    return q


def _number(v, *, field: str, minimum: float | None = None) -> float:
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise DistributionError(f"{field}: expected a number, got {v!r}")
    f = float(v)
    if not math.isfinite(f):
        raise DistributionError(f"{field}: must be finite, got {v!r}")
    if minimum is not None and f < minimum:
        raise DistributionError(f"{field}: must be >= {minimum:g}, got {v!r}")
    return f


def parse_distribution(resource: str, data) -> UsageDistribution:
    """One ``{dist: ..., ...}`` block → a validated distribution.

    ``resource`` (``"cpu"``/``"memory"``) selects the quantity codec.
    A bare quantity (string or int) is shorthand for a point
    distribution at that value.
    """
    field = f"usage.{resource}"
    if isinstance(data, (str, int)) and not isinstance(data, bool):
        return UsageDistribution(
            kind="point", value=_usage_value(resource, data, field=field)
        )
    if not isinstance(data, dict):
        raise DistributionError(
            f"{field}: expected a distribution mapping, got {data!r}"
        )
    kind = data.get("dist")
    if kind not in DIST_KINDS:
        raise DistributionError(
            f"{field}: dist must be one of {DIST_KINDS}, got {kind!r}"
        )
    known = {"point": {"dist", "value"},
             "normal": {"dist", "mean", "std"},
             "lognormal": {"dist", "mean", "sigma"},
             "empirical": {"dist", "values", "weights"}}[kind]
    extra = set(data) - known
    if extra:
        raise DistributionError(
            f"{field}: unknown field(s) {sorted(extra)} for dist "
            f"{kind!r} (want {sorted(known - {'dist'})})"
        )
    if kind == "point":
        if "value" not in data:
            raise DistributionError(f"{field}: point needs 'value'")
        return UsageDistribution(
            kind="point",
            value=_usage_value(resource, data["value"], field=f"{field}.value"),
        )
    if kind == "normal":
        if "mean" not in data:
            raise DistributionError(f"{field}: normal needs 'mean'")
        mean = float(
            _usage_value(resource, data["mean"], field=f"{field}.mean")
        )
        std = (
            float(_quantity(resource, data["std"], field=f"{field}.std"))
            if isinstance(data.get("std"), str)
            else _number(data.get("std", 0), field=f"{field}.std", minimum=0.0)
        )
        return UsageDistribution(kind="normal", mean=mean, std=std)
    if kind == "lognormal":
        if "mean" not in data:
            raise DistributionError(f"{field}: lognormal needs 'mean'")
        mean = float(
            _usage_value(resource, data["mean"], field=f"{field}.mean")
        )
        sigma = _number(
            data.get("sigma", 0), field=f"{field}.sigma", minimum=0.0
        )
        if sigma > 4.0:
            raise DistributionError(
                f"{field}.sigma: must be <= 4 (exp(4σ) already exceeds "
                f"any sane usage spread), got {sigma:g}"
            )
        return UsageDistribution(kind="lognormal", mean=mean, sigma=sigma)
    # empirical
    raw_values = data.get("values")
    if not isinstance(raw_values, list) or not raw_values:
        raise DistributionError(
            f"{field}: empirical needs a non-empty 'values' list"
        )
    values = tuple(
        _usage_value(resource, v, field=f"{field}.values[{i}]")
        for i, v in enumerate(raw_values)
    )
    raw_weights = data.get("weights")
    if raw_weights is None:
        weights = tuple(1.0 for _ in values)
    else:
        if not isinstance(raw_weights, list) or len(raw_weights) != len(values):
            raise DistributionError(
                f"{field}: weights must be a list the length of values"
            )
        weights = tuple(
            _number(w, field=f"{field}.weights[{i}]")
            for i, w in enumerate(raw_weights)
        )
        if any(w <= 0 for w in weights):
            raise DistributionError(f"{field}: weights must be > 0")
    return UsageDistribution(kind="empirical", values=values, weights=weights)


def parse_stochastic_spec(data) -> StochasticSpec:
    """A spec document/wire body → :class:`StochasticSpec`.

    Shape::

        usage:
          cpu:    {dist: normal, mean: 500m, std: 150m}
          memory: {dist: lognormal, mean: 1gb, sigma: 0.4}
        replicas: "40"        # reference grammar (or a plain int)
        samples: 256          # optional; default KCCAP_CAR_SAMPLES/64
        seed: 7               # optional; explicit, never wall-clock
        confidence: 0.95      # optional; the -car-spec exit bar
    """
    if not isinstance(data, dict):
        raise DistributionError(f"spec: expected a mapping, got {data!r}")
    extra = set(data) - {"usage", "replicas", "samples", "seed", "confidence"}
    if extra:
        raise DistributionError(f"spec: unknown field(s) {sorted(extra)}")
    usage = data.get("usage")
    if not isinstance(usage, dict):
        raise DistributionError("spec: needs a 'usage' mapping")
    extra = set(usage) - {"cpu", "memory"}
    if extra:
        raise DistributionError(
            f"usage: unknown resource(s) {sorted(extra)} (want cpu/memory)"
        )
    if "cpu" not in usage or "memory" not in usage:
        raise DistributionError("usage: needs both 'cpu' and 'memory'")
    cpu = parse_distribution("cpu", usage["cpu"])
    memory = parse_distribution("memory", usage["memory"])
    replicas = data.get("replicas", 1)
    if isinstance(replicas, str):
        try:
            replicas = int(replicas)
        except ValueError:
            raise DistributionError(f"spec: bad replicas {data['replicas']!r}")
    if isinstance(replicas, bool) or not isinstance(replicas, int):
        raise DistributionError(f"spec: bad replicas {data['replicas']!r}")
    samples = data.get("samples", 0)
    if isinstance(samples, bool) or not isinstance(samples, int):
        raise DistributionError("spec: samples must be an integer")
    if samples and not 2 <= samples <= _MAX_SAMPLES:
        raise DistributionError(
            f"spec: samples must be in [2, {_MAX_SAMPLES}], got {samples}"
        )
    seed = data.get("seed", 0)
    if isinstance(seed, bool) or not isinstance(seed, int):
        raise DistributionError("spec: seed must be an integer")
    confidence = _number(
        data.get("confidence", 0.95), field="spec.confidence"
    )
    if not 0.0 < confidence < 1.0:
        raise DistributionError(
            f"spec: confidence must be in (0, 1), got {confidence:g}"
        )
    return StochasticSpec(
        cpu=cpu,
        memory=memory,
        replicas=replicas,
        samples=samples,
        seed=seed,
        confidence=confidence,
    )


def load_stochastic_spec(path: str) -> StochasticSpec:
    """Load ``path`` (YAML when PyYAML is present, else strict JSON) —
    the same loader split as the watchlist's."""
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    try:
        import yaml  # type: ignore[import-untyped]

        data = yaml.safe_load(text)
    except ImportError:
        try:
            data = json.loads(text)
        except ValueError as e:
            raise DistributionError(
                f"{path}: not valid JSON (and PyYAML is unavailable): {e}"
            ) from e
    except Exception as e:  # yaml.YAMLError — malformed document
        raise DistributionError(f"{path}: cannot parse: {e}") from e
    return parse_stochastic_spec(data)


# -- the deterministic sampler ---------------------------------------------

def sample_key(seed: int, stream: int) -> jax.Array:
    """The counter-based key for one (seed, stream) draw: an explicit
    integer seed folded with the stream index (cpu=0, memory=1), so two
    resources of one spec never share a sample sequence and every run
    with the same seed replays the identical draws."""
    return jax.random.fold_in(jax.random.PRNGKey(int(seed)), int(stream))


@partial(jax.jit, static_argnames=("n",))
def _normal_samples(key, mean, std, n):
    z = jax.random.normal(key, (n,), dtype=jnp.float64)
    v = jnp.round(mean + std * z)
    return jnp.clip(v, 1.0, float(MAX_USAGE)).astype(jnp.int64)


@partial(jax.jit, static_argnames=("n",))
def _lognormal_samples(key, mu, sigma, n):
    z = jax.random.normal(key, (n,), dtype=jnp.float64)
    v = jnp.round(jnp.exp(mu + sigma * z))
    return jnp.clip(v, 1.0, float(MAX_USAGE)).astype(jnp.int64)


@partial(jax.jit, static_argnames=("n",))
def _empirical_samples(key, cdf, values, n):
    u = jax.random.uniform(key, (n,), dtype=jnp.float64)
    idx = jnp.searchsorted(cdf, u, side="right")
    return values[jnp.clip(idx, 0, values.shape[0] - 1)]


def sample_usage(dist: UsageDistribution, n: int, key) -> np.ndarray:
    """Draw ``n`` usage samples — ``[n]`` int64 in ``[1, 2^62]``.

    Host wrapper over the jit-pure draw kernels: the transformation
    (affine / exp / inverse-CDF) runs traced, the materialization is the
    single host sync.  Deterministic in ``(dist, n, key)``.
    """
    if n < 1:
        raise ValueError(f"need at least 1 sample, got {n}")
    if dist.kind == "point":
        return np.full(n, dist.value, dtype=np.int64)
    if dist.kind == "normal":
        return np.asarray(_normal_samples(key, dist.mean, dist.std, n))
    if dist.kind == "lognormal":
        return np.asarray(
            _lognormal_samples(key, math.log(dist.mean), dist.sigma, n)
        )
    weights = np.asarray(dist.weights, dtype=np.float64)
    cdf = np.cumsum(weights) / weights.sum()
    values = np.asarray(dist.values, dtype=np.int64)
    return np.asarray(_empirical_samples(key, cdf, values, n))
