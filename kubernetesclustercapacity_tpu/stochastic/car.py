"""Capacity-at-risk: Monte Carlo capacity quantiles under usage uncertainty.

The question operators actually ask is not "how many replicas fit if
every pod uses exactly its request" but "how many fit with 95%
confidence".  This module answers it by drawing ``S`` per-pod usage
samples from the spec's distributions (:mod:`.distributions` — explicit
seeds, replayable) and materializing them as a **leading sample axis
over the existing fit kernels**: each sample is one row of a
:class:`~..scenario.ScenarioGrid`, so the whole Monte Carlo pass is ONE
``sweep_snapshot`` dispatch — which routes through the device cache,
the shape-bucket ladder (PR 4) and the count-weighted (shape, count)
grouped kernels (PR 9) unchanged.  Those paths are pinned bit-exact
against each other, so the capacity quantiles are **deterministic in
the seed alone**: grouped or ungrouped, bucketed or unbucketed, the
same seed yields bit-identical quantiles.

The reduction (order statistics over the per-sample totals) is
host-side numpy — sampling stays jit-pure, reduction never traces.

Quantile rule (shared with the numpy seed-replay oracle, documented so
both sides implement it independently): with the ``S`` totals sorted
ascending, the capacity at confidence ``q`` is the order statistic at
index ``S - ceil(q·S)`` — the largest capacity ``c`` in the sample set
with ``#{samples >= c} / S >= q``.  Pure integer selection on int64
totals: no interpolation, no float capacity, bit-exact by construction.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field

import numpy as np

from kubernetesclustercapacity_tpu.ops.fit import (
    sweep_quantiles_snapshot,
    sweep_snapshot,
)
from kubernetesclustercapacity_tpu.scenario import ScenarioGrid
from kubernetesclustercapacity_tpu.snapshot import ClusterSnapshot
from kubernetesclustercapacity_tpu.stochastic.distributions import (
    StochasticSpec,
    sample_key,
    sample_usage,
)

__all__ = [
    "DEFAULT_QUANTILES",
    "CaRResult",
    "capacity_at_risk",
    "car_oracle",
    "fit_totals_numpy",
    "quantile_index",
    "quantile_label",
]

#: The reporting ladder: median, and the three confidence levels
#: capacity planning actually quotes.
DEFAULT_QUANTILES = (0.5, 0.9, 0.95, 0.99)


def quantile_index(n: int, q: float) -> int:
    """Sorted-ascending index of the capacity at confidence ``q``.

    ``i = n - ceil(q·n)`` (clamped to ``[0, n-1]``): at least a ``q``
    fraction of samples sit at or above the returned order statistic.
    ``q·n`` is rounded to 9 decimals before the ceil so binary float
    noise (``0.9 * 10 == 9.000000000000002``) cannot shift the index.
    """
    if not 0.0 < q < 1.0:
        raise ValueError(f"quantile must be in (0, 1), got {q}")
    if n < 1:
        raise ValueError(f"need at least 1 sample, got {n}")
    k = math.ceil(round(q * n, 9))
    return min(max(n - k, 0), n - 1)


def quantile_label(q: float) -> str:
    """``0.95`` → ``"p95"`` (the wire/report spelling)."""
    return f"p{q * 100:g}"


@dataclass
class CaRResult:
    """One capacity-at-risk evaluation (numpy arrays throughout).

    ``totals`` is the ``[S]`` per-sample cluster capacity;
    ``quantiles`` maps confidence → capacity (int replicas) and
    ``quantile_samples`` maps confidence → the sample index realizing
    it (the scenario the per-quantile binding attribution explains).
    """

    spec: StochasticSpec
    mode: str
    n_samples: int
    samples_cpu: np.ndarray  # [S] int64 per-pod cpu usage draws
    samples_mem: np.ndarray  # [S] int64 per-pod memory usage draws
    totals: np.ndarray  # [S] int64 capacity per sample
    quantiles: dict[float, int]
    quantile_samples: dict[float, int]
    mean: float
    prob_fit: float
    eval_ms: float = 0.0
    bindings: dict[float, dict[str, int]] = field(default_factory=dict)

    def quantile(self, q: float) -> int:
        return self.quantiles[q]

    @property
    def schedulable(self) -> bool:
        """True when the spec's replicas fit at its confidence bar."""
        return self.prob_fit >= self.spec.confidence

    def to_wire(self) -> dict:
        """The ``car`` op's response body (and the offline report's
        input) — quantiles keyed by their ``pNN`` labels."""
        return {
            "mode": self.mode,
            "samples": self.n_samples,
            "seed": self.spec.seed,
            "replicas": self.spec.replicas,
            "confidence": self.spec.confidence,
            "quantiles": {
                quantile_label(q): int(v)
                for q, v in sorted(self.quantiles.items())
            },
            "mean": round(self.mean, 3),
            "prob_fit": round(self.prob_fit, 6),
            "schedulable": self.schedulable,
            "min_total": int(self.totals.min()),
            "max_total": int(self.totals.max()),
            "binding": {
                quantile_label(q): dict(counts)
                for q, counts in sorted(self.bindings.items())
            },
            "usage": {
                "cpu": self.spec.cpu.to_wire(),
                "memory": self.spec.memory.to_wire(),
            },
        }


def capacity_at_risk(
    snapshot: ClusterSnapshot,
    spec: StochasticSpec,
    *,
    mode: str | None = None,
    node_mask=None,
    quantiles: tuple[float, ...] = DEFAULT_QUANTILES,
    bindings: bool = True,
    fused: bool = True,
) -> CaRResult:
    """Evaluate one stochastic spec against a snapshot.

    Draws ``spec.n_samples()`` (cpu, memory) usage pairs from the
    spec's seed, dispatches them as one ``[S]``-scenario sweep through
    the production kernel path (grouped/bucketed/cached exactly like a
    live sweep — same node_mask conventions, same semantics modes), and
    reduces the per-sample totals to capacity quantiles, the mean, and
    the probability of fitting ``spec.replicas``.

    ``fused=True`` (the default) runs the sweep AND the order-statistic
    reduction as ONE device launch (:func:`..ops.fit.sweep_quantiles_snapshot`):
    the quantile indices are computed host-side from ``(S, q)`` alone
    and gathered from an on-device stable argsort — a stable sort's
    permutation is algorithm-independent, so the quantile values and
    realizing sample indices are bit-identical to the host-side
    ``np.argsort(kind="stable")`` reduction (``fused=False``, the
    pre-fusion path, kept as the oracle twin and pinned equal by test).

    ``bindings=True`` additionally explains the quantile-realizing
    scenarios (one explain pass over ``len(quantiles)`` rows): which
    constraint binds at P95 vs P50 — the per-quantile attribution the
    ``car`` surfaces report.
    """
    mode = mode or snapshot.semantics
    n = spec.n_samples()
    t0 = time.perf_counter()
    cpu = sample_usage(spec.cpu, n, sample_key(spec.seed, 0))
    mem = sample_usage(spec.memory, n, sample_key(spec.seed, 1))
    grid = ScenarioGrid(
        cpu_request_milli=cpu,
        mem_request_bytes=mem,
        replicas=np.full(n, int(spec.replicas), dtype=np.int64),
    )
    qvals: dict[float, int] = {}
    qsamples: dict[float, int] = {}
    if fused:
        qs = tuple(quantiles)
        q_indices = tuple(quantile_index(n, q) for q in qs)
        totals, sched, qv, qx, _kernel = sweep_quantiles_snapshot(
            snapshot, grid, mode=mode, node_mask=node_mask,
            q_indices=q_indices,
        )
        totals = np.asarray(totals, dtype=np.int64)
        for j, q in enumerate(qs):
            qvals[q] = int(qv[j])
            qsamples[q] = int(qx[j])
    else:
        totals, sched = sweep_snapshot(
            snapshot, grid, mode=mode, node_mask=node_mask
        )
        totals = np.asarray(totals, dtype=np.int64)
        # Host-side reduction: a stable argsort so the quantile-realizing
        # SAMPLE index (not just the value) is deterministic under ties.
        order = np.argsort(totals, kind="stable")
        sorted_totals = totals[order]
        for q in quantiles:
            i = quantile_index(n, q)
            qvals[q] = int(sorted_totals[i])
            qsamples[q] = int(order[i])
    result = CaRResult(
        spec=spec,
        mode=mode,
        n_samples=n,
        samples_cpu=cpu,
        samples_mem=mem,
        totals=totals,
        quantiles=qvals,
        quantile_samples=qsamples,
        mean=float(totals.astype(np.float64).mean()),
        prob_fit=float(np.asarray(sched, dtype=bool).mean()),
    )
    if bindings and quantiles:
        from kubernetesclustercapacity_tpu.explain import explain_snapshot

        qs = sorted(qvals)
        qgrid = ScenarioGrid(
            cpu_request_milli=cpu[[qsamples[q] for q in qs]],
            mem_request_bytes=mem[[qsamples[q] for q in qs]],
            replicas=np.full(len(qs), int(spec.replicas), dtype=np.int64),
        )
        ex = explain_snapshot(snapshot, qgrid, mode=mode, node_mask=node_mask)
        result.bindings = {
            q: ex.binding_counts(i) for i, q in enumerate(qs)
        }
    result.eval_ms = (time.perf_counter() - t0) * 1e3
    return result


def fit_totals_numpy(
    alloc_cpu,
    alloc_mem,
    alloc_pods,
    used_cpu,
    used_mem,
    pods_count,
    healthy,
    cpu_reqs,
    mem_reqs,
    *,
    mode: str = "reference",
    node_mask=None,
    counts=None,
    chunk: int = 8,
) -> np.ndarray:
    """The numpy seed-replay oracle's sweep: per-sample cluster totals
    computed with pure numpy — the same Go-faithful arithmetic as
    :func:`~..ops.fit.fit_per_node` (uint64 CPU compare/divide on the
    raw bit patterns, int64 wrap-around memory with truncating
    division, the Q1 conditional pod-cap overwrite) with **no JAX in
    the loop**, so the kernel path has an independent comparator even
    at 1M-node scale where the sequential Python oracle cannot go.

    ``counts`` (optional ``[N]`` int64) weights each row's fit — the
    grouped (shape, count) vocabulary; ``None`` weights every row 1.
    Scenario rows are processed in ``chunk``-sized slabs to bound the
    ``[chunk, N]`` intermediates.  Returns ``[S]`` int64 totals.
    """
    alloc_cpu_u = np.asarray(alloc_cpu, dtype=np.int64).astype(np.uint64)
    used_cpu_u = np.asarray(used_cpu, dtype=np.int64).astype(np.uint64)
    alloc_mem = np.asarray(alloc_mem, dtype=np.int64)
    used_mem = np.asarray(used_mem, dtype=np.int64)
    alloc_pods = np.asarray(alloc_pods, dtype=np.int64)
    pods_count = np.asarray(pods_count, dtype=np.int64)
    healthy_b = np.asarray(healthy, dtype=bool)
    cpu_reqs = np.asarray(cpu_reqs, dtype=np.int64)
    mem_reqs = np.asarray(mem_reqs, dtype=np.int64)
    weights = (
        np.ones(alloc_cpu_u.shape[0], dtype=np.int64)
        if counts is None
        else np.asarray(counts, dtype=np.int64)
    )
    if node_mask is not None:
        mask = np.asarray(node_mask, dtype=bool)
    else:
        mask = None
    s = cpu_reqs.shape[0]
    totals = np.zeros(s, dtype=np.int64)
    mem_head = alloc_mem - used_mem  # wraps like Go int64 (silent in C)
    with np.errstate(over="ignore"):
        for lo in range(0, s, max(chunk, 1)):
            hi = min(lo + max(chunk, 1), s)
            cr = cpu_reqs[lo:hi].astype(np.uint64)[:, None]
            cr = np.maximum(cr, np.uint64(1))
            mr = mem_reqs[lo:hi][:, None]
            cpu_fit = np.where(
                alloc_cpu_u[None, :] <= used_cpu_u[None, :],
                np.uint64(0),
                (alloc_cpu_u[None, :] - used_cpu_u[None, :]) // cr,
            ).astype(np.int64)
            den = np.where(mr == 0, np.int64(1), mr)
            q = mem_head[None, :] // den  # numpy floors; fix to truncate
            r = mem_head[None, :] - q * den
            fix = ((r != 0) & ((mem_head[None, :] < 0) != (den < 0)))
            mem_fit = np.where(
                alloc_mem[None, :] <= used_mem[None, :],
                np.int64(0),
                q + fix.astype(np.int64),
            )
            fit = np.minimum(cpu_fit, mem_fit)
            if mode == "reference":
                fit = np.where(
                    fit >= alloc_pods[None, :],
                    alloc_pods[None, :] - pods_count[None, :],
                    fit,
                )
            elif mode == "strict":
                slots = np.maximum(
                    alloc_pods[None, :] - pods_count[None, :], np.int64(0)
                )
                fit = np.maximum(np.minimum(fit, slots), np.int64(0))
                fit = np.where(healthy_b[None, :], fit, np.int64(0))
            else:
                raise ValueError(f"unknown mode {mode!r}")
            if mask is not None:
                fit = np.where(mask[None, :], fit, np.int64(0))
            totals[lo:hi] = (fit * weights[None, :]).sum(axis=1)
    return totals


def car_oracle(
    snapshot: ClusterSnapshot,
    spec: StochasticSpec,
    *,
    mode: str | None = None,
    node_mask=None,
    quantiles: tuple[float, ...] = DEFAULT_QUANTILES,
) -> CaRResult:
    """The full seed-replay oracle: re-draw the identical samples from
    the identical seed, sweep them with :func:`fit_totals_numpy`
    (numpy, ungrouped, unbucketed), reduce with the documented quantile
    rule.  ``car_parity_diffs == 0`` in bench and the randomized tests
    means :func:`capacity_at_risk` and this function agree bit-for-bit.
    """
    mode = mode or snapshot.semantics
    n = spec.n_samples()
    cpu = sample_usage(spec.cpu, n, sample_key(spec.seed, 0))
    mem = sample_usage(spec.memory, n, sample_key(spec.seed, 1))
    totals = fit_totals_numpy(
        snapshot.alloc_cpu_milli,
        snapshot.alloc_mem_bytes,
        snapshot.alloc_pods,
        snapshot.used_cpu_req_milli,
        snapshot.used_mem_req_bytes,
        snapshot.pods_count,
        snapshot.healthy,
        cpu,
        mem,
        mode=mode,
        node_mask=node_mask,
    )
    order = np.argsort(totals, kind="stable")
    sorted_totals = totals[order]
    qvals = {q: int(sorted_totals[quantile_index(n, q)]) for q in quantiles}
    qsamples = {
        q: int(order[quantile_index(n, q)]) for q in quantiles
    }
    return CaRResult(
        spec=spec,
        mode=mode,
        n_samples=n,
        samples_cpu=cpu,
        samples_mem=mem,
        totals=totals,
        quantiles=qvals,
        quantile_samples=qsamples,
        mean=float(totals.astype(np.float64).mean()),
        prob_fit=float((totals >= int(spec.replicas)).mean()),
    )
