"""Empirical usage from the audit log: forecasts from replayable history.

The PR-6 audit log records every published snapshot generation as a
digest-chained checkpoint/diff stream.  Each reconstructed generation
carries per-node ``used_*`` totals and ``pods_count`` — so the observed
**per-pod** usage of a generation is ``used / pods`` per node, weighted
by how many pods produced it.  This module walks that history (through
:class:`~..audit.log.AuditReader`, digest-verifying every
reconstruction) and folds the observations into an empirical
:class:`~.distributions.UsageDistribution`, making capacity-at-risk
forecasts a *derived view of replayable history*: the same audit
directory always yields the same distribution, and ``kccap -replay``
can prove the inputs.

Robustness contract (the satellite): a directory with no segments, a
segment holding only a torn tail, or generations with zero usage
observations yields a typed :class:`InsufficientHistoryError` carrying
what WAS found — never an empty-array crash, and never a silent point
fallback that would quietly collapse every quantile to the plain fit.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from kubernetesclustercapacity_tpu.audit.log import AuditError, AuditReader
from kubernetesclustercapacity_tpu.stochastic.distributions import (
    MAX_USAGE,
    UsageDistribution,
)

__all__ = [
    "InsufficientHistoryError",
    "SeriesHistory",
    "UsageHistory",
    "extract_series",
    "extract_usage_history",
]

_RESOURCES = ("cpu", "memory")

#: Series kinds :func:`extract_series` can walk out of the audit log:
#: ``usage`` is the demand side (cluster-wide requested totals), and
#: ``allocatable`` the supply side (what the fleet could hold) — a trend
#: fit needs both, because "when do we run out" is a question about the
#: gap, not either line alone.
_SERIES_KINDS = ("usage", "allocatable")

_SERIES_FIELDS = {
    ("cpu", "usage"): "used_cpu_req_milli",
    ("memory", "usage"): "used_mem_req_bytes",
    ("cpu", "allocatable"): "alloc_cpu_milli",
    ("memory", "allocatable"): "alloc_mem_bytes",
    ("pods", "usage"): "pods_count",
    ("pods", "allocatable"): "alloc_pods",
}


class InsufficientHistoryError(RuntimeError):
    """The audit history holds too little observed usage to build a
    distribution.  Typed so callers can branch on it (fall back to an
    explicit operator-provided distribution — never silently to a
    point); carries what the walk DID find."""

    def __init__(
        self, reason: str, *, generations: int = 0, observations: int = 0
    ) -> None:
        super().__init__(
            f"insufficient usage history: {reason} "
            f"(generations={generations}, observations={observations})"
        )
        self.reason = reason
        self.generations = generations
        self.observations = observations


@dataclass(frozen=True)
class UsageHistory:
    """Aggregated per-pod usage observations for one resource.

    ``values``/``weights`` are the distinct observed per-pod usage
    values and their pod-weighted multiplicities; ``observations`` is
    the total pod-weight, ``generations`` how many audit generations
    contributed.
    """

    resource: str
    values: np.ndarray  # [K] int64, ascending
    weights: np.ndarray  # [K] float64, > 0
    observations: int
    generations: int

    def distribution(self) -> UsageDistribution:
        """The empirical distribution the sampler consumes."""
        return UsageDistribution(
            kind="empirical",
            values=tuple(int(v) for v in self.values),
            weights=tuple(float(w) for w in self.weights),
        )

    def to_wire(self) -> dict:
        return {
            "resource": self.resource,
            "distinct_values": int(self.values.shape[0]),
            "observations": self.observations,
            "generations": self.generations,
        }


def _load_reader(source) -> AuditReader:
    if isinstance(source, AuditReader):
        return source
    try:
        return AuditReader.load(source)
    except AuditError as e:
        # No segments at all (empty/missing dir) IS an insufficient-
        # history outcome for a forecaster; mid-file corruption stays a
        # hard AuditError — a damaged log is an incident, not a lack of
        # data.
        if "no audit segments" in str(e) or "cannot read audit dir" in str(e):
            raise InsufficientHistoryError(str(e)) from e
        raise


def extract_usage_history(
    source,
    resource: str = "cpu",
    *,
    min_observations: int = 8,
) -> UsageHistory:
    """Walk an audit log (directory path or loaded
    :class:`~..audit.log.AuditReader`) into a :class:`UsageHistory`.

    Every recorded generation reconstructs through the digest-verified
    replay path; per node with ``pods_count > 0`` the observation is
    ``used // pods`` (one per pod, so a 40-pod node weighs 40× a 1-pod
    node).  Wrapped/degenerate carriers (negative usage, zero per-pod
    values) are excluded — they are codec artifacts, not usage.
    Raises :class:`InsufficientHistoryError` when fewer than
    ``min_observations`` pod-observations survive.
    """
    if resource not in _RESOURCES:
        raise ValueError(
            f"resource must be one of {_RESOURCES}, got {resource!r}"
        )
    reader = _load_reader(source)
    gens = reader.generations()
    if not gens:
        raise InsufficientHistoryError(
            "the audit log holds no generation records "
            "(segments empty or only a torn tail)",
        )
    used_field = (
        "used_cpu_req_milli" if resource == "cpu" else "used_mem_req_bytes"
    )
    tally: dict[int, float] = {}
    observations = 0
    contributing = 0
    for rec in gens:
        snap = reader.snapshot_at(rec["generation"])
        used = np.asarray(getattr(snap, used_field), dtype=np.int64)
        pods = np.asarray(snap.pods_count, dtype=np.int64)
        ok = (pods > 0) & (used > 0)
        if not ok.any():
            continue
        per_pod = used[ok] // pods[ok]
        weight = pods[ok]
        keep = (per_pod >= 1) & (per_pod <= MAX_USAGE)
        if not keep.any():
            continue
        contributing += 1
        for v, w in zip(per_pod[keep], weight[keep]):
            tally[int(v)] = tally.get(int(v), 0.0) + float(w)
            observations += int(w)
    if observations < max(min_observations, 1):
        raise InsufficientHistoryError(
            f"only {observations} pod-usage observation(s) across "
            f"{len(gens)} generation(s); need >= {min_observations}",
            generations=len(gens),
            observations=observations,
        )
    values = np.array(sorted(tally), dtype=np.int64)
    weights = np.array([tally[int(v)] for v in values], dtype=np.float64)
    return UsageHistory(
        resource=resource,
        values=values,
        weights=weights,
        observations=observations,
        generations=contributing,
    )


@dataclass(frozen=True)
class SeriesHistory:
    """A per-generation cluster-wide total as a time series.

    ``ts`` is the time axis in seconds (the generation records' own
    wall-clock stamps — never re-sampled at load time, so the same audit
    directory always yields the same series); ``totals`` the cluster-wide
    sum of the selected column per generation, as float64 (sums of int64
    columns can exceed the int64 range on wrapped carriers — the trend
    fit is statistical, not bit-exact arithmetic).

    ``degraded_time_axis`` is True when the recorded timestamps were
    unusable (non-monotone, missing, or zero-span): the series falls
    back to RECORD ORDER (``ts = 0, 1, 2, ...``) rather than crashing or
    silently mis-ordering — a trend fitted on a degraded axis is still a
    trend per *generation*, just not per second, and every downstream
    surface carries the flag.
    """

    resource: str
    kind: str
    ts: np.ndarray  # [T] float64 seconds
    totals: np.ndarray  # [T] float64 cluster-wide totals
    generations: np.ndarray  # [T] int64 generation numbers
    degraded_time_axis: bool

    def to_wire(self) -> dict:
        return {
            "resource": self.resource,
            "kind": self.kind,
            "points": int(self.ts.shape[0]),
            "span_s": float(self.ts[-1] - self.ts[0])
            if self.ts.shape[0]
            else 0.0,
            "degraded_time_axis": self.degraded_time_axis,
        }


def extract_series(
    source,
    resource: str = "cpu",
    kind: str = "usage",
    *,
    min_points: int = 2,
) -> SeriesHistory:
    """Walk an audit log into a per-generation total time series.

    ``resource`` is ``cpu``/``memory``/``pods``; ``kind`` selects the
    demand column (``usage``: the ``used_*`` requested totals) or the
    supply column (``allocatable``).  Every generation reconstructs
    through the digest-verified replay path; totals are summed with
    Python ints (no int64 overflow on wrapped carriers) and returned as
    float64.

    Timestamps are verified monotone non-decreasing with a positive
    span; otherwise the series degrades to record order with
    ``degraded_time_axis=True`` (see :class:`SeriesHistory`).  Raises
    :class:`InsufficientHistoryError` with what WAS found when fewer
    than ``min_points`` generations exist.
    """
    field_name = _SERIES_FIELDS.get((resource, kind))
    if field_name is None:
        raise ValueError(
            f"unknown series ({resource!r}, {kind!r}); resource must be "
            "cpu/memory/pods and kind one of "
            f"{_SERIES_KINDS}"
        )
    reader = _load_reader(source)
    gens = reader.generations()
    if len(gens) < max(min_points, 1):
        raise InsufficientHistoryError(
            f"only {len(gens)} generation record(s); a series needs "
            f">= {min_points}",
            generations=len(gens),
        )
    ts: list[float] = []
    totals: list[float] = []
    numbers: list[int] = []
    for rec in gens:
        snap = reader.snapshot_at(rec["generation"])
        col = np.asarray(getattr(snap, field_name), dtype=np.int64)
        totals.append(float(sum(int(v) for v in col)))
        raw_ts = rec.get("ts")
        ts.append(float(raw_ts) if isinstance(raw_ts, (int, float)) else -1.0)
        numbers.append(int(rec["generation"]))
    axis = np.asarray(ts, dtype=np.float64)
    degraded = bool(
        np.any(axis < 0)
        or np.any(np.diff(axis) < 0)
        or axis[-1] <= axis[0]
    )
    if degraded:
        axis = np.arange(len(ts), dtype=np.float64)
    return SeriesHistory(
        resource=resource,
        kind=kind,
        ts=axis,
        totals=np.asarray(totals, dtype=np.float64),
        generations=np.asarray(numbers, dtype=np.int64),
        degraded_time_axis=degraded,
    )
