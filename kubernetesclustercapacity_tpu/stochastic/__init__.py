"""Stochastic capacity (ROADMAP item 2): capacity-at-risk under usage
uncertainty.

Point requests are fiction in production; this package models per-pod
usage as distributions and answers "how many replicas fit with 95%
confidence" via a Monte Carlo sample axis over the existing fit
kernels:

* :mod:`.distributions` — the point/normal/lognormal/empirical
  vocabulary, the watchlist-grammar loader, and the deterministic
  counter-based sampler (``jax.random`` with explicit seeds — every
  run replayable);
* :mod:`.car` — the capacity-at-risk engine: samples → one
  ``[S]``-scenario sweep through the production kernel path
  (devcache/bucketing/grouping apply unchanged) → host-side quantile
  reduction, pinned bit-exact against a numpy seed-replay oracle;
* :mod:`.history` — the empirical feed: observed per-pod usage
  extracted from the audit log's digest-verified generations, so
  forecasts derive from replayable history.
"""

from kubernetesclustercapacity_tpu.stochastic.car import (  # noqa: F401
    DEFAULT_QUANTILES,
    CaRResult,
    capacity_at_risk,
    car_oracle,
    fit_totals_numpy,
    quantile_index,
    quantile_label,
)
from kubernetesclustercapacity_tpu.stochastic.distributions import (  # noqa: F401
    DistributionError,
    StochasticSpec,
    UsageDistribution,
    default_samples,
    load_stochastic_spec,
    parse_distribution,
    parse_stochastic_spec,
    sample_key,
    sample_usage,
)
from kubernetesclustercapacity_tpu.stochastic.history import (  # noqa: F401
    InsufficientHistoryError,
    SeriesHistory,
    UsageHistory,
    extract_series,
    extract_usage_history,
)
