"""Resilience primitives shared by every network boundary.

The service stack has three places where a transient failure must not
become a wrong answer or a thundering herd: the client's socket (retry
idempotent ops with backoff, never retry mutations), the server's
dispatch (shed requests whose caller already gave up), and long-lived
degradation decisions (stop re-paying a deterministic failure on every
request).  This module is the ONE implementation of those three shapes —
:class:`RetryPolicy`, :class:`Deadline`, :class:`CircuitBreaker` — so
the client, the fused-kernel path, and the follower's relist loop all
back off and trip the same way.

Backoff is exponential with *decorrelated jitter* (the AWS architecture
blog's variant): each delay is drawn uniformly from ``[base, prev * 3]``
and capped, so a fleet of clients re-syncing after a shared outage
spreads out instead of stampeding in lockstep — the failure mode
constraint-packing services hit when many frontends relist against one
scheduler endpoint.
"""

from __future__ import annotations

import random
import threading
import time

__all__ = [
    "RetryPolicy",
    "Deadline",
    "DeadlineExpired",
    "CircuitBreaker",
    "CircuitOpenError",
    "RetryableElsewhere",
    "OverloadedError",
    "DrainingError",
    "NotLeaderError",
    "ClusterLostError",
    "TenantQuotaError",
    "TokenBucket",
    "WIRE_CODES",
    "decorrelated_jitter",
]


class DeadlineExpired(TimeoutError):
    """The caller's time budget ran out before the operation completed."""


class CircuitOpenError(ConnectionError):
    """Fail-fast refusal: the breaker is open and the cooldown has not
    elapsed — the protected operation was not attempted at all."""


class RetryableElsewhere(RuntimeError):
    """The server REFUSED this request before doing any work on it.

    The defining property: the operation provably did not execute, so a
    retry — even of a mutation — cannot double-apply it.  A multi-
    endpoint client (:class:`~.service.replicaset.ReplicaSet`) treats
    every subclass as "try the next replica"; a single-endpoint client
    surfaces it unchanged (retrying the same refusing server would just
    add load to whatever made it refuse).  Deliberately NOT an
    ``OSError``/``ConnectionError`` subclass: the transport worked fine,
    so :meth:`RetryPolicy.is_transport_error` must not classify it as a
    broken socket and re-send on the same connection.

    ``wire_code`` is the machine-readable refusal class the server
    stamps into the error envelope (``{"ok": false, "code": ...}``) so
    clients dispatch on a stable token, never on error prose.
    """

    wire_code = "refused"


class OverloadedError(RetryableElsewhere):
    """503-style admission refusal: the server's admission controller
    (concurrency limit or rps token bucket) shed the request before any
    dispatch work."""

    wire_code = "overloaded"


class DrainingError(RetryableElsewhere):
    """The server is draining (SIGTERM / ``drain_server`` op): it is
    finishing in-flight work but accepting no new compute or mutation
    requests.  Route to another replica."""

    wire_code = "draining"


class NotLeaderError(RetryableElsewhere):
    """A mutation (``update``/``reload``) reached a plane REPLICA, which
    serves a read-only view of the leader's snapshot stream.  Route the
    mutation to the leader."""

    wire_code = "not_leader"


class ClusterLostError(RetryableElsewhere):
    """A federation endpoint reports the queried cluster as ``lost``:
    its stream has been silent past the eviction horizon, so this
    endpoint holds no servable view of it — not even an explicitly-stale
    one.  The refusal happened before any work, so another federation
    endpoint (which may still hold a within-horizon view) is safe to
    try; multi-endpoint clients demote the refusing endpoint the way
    they demote a draining one."""

    wire_code = "cluster_lost"


class TenantQuotaError(RetryableElsewhere):
    """The calling tenant exceeded ITS OWN quota (per-tenant rps cap or
    concurrency share) at admission.  The refusal happened before any
    work — but unlike ``overloaded`` it is AUTHORITATIVE, not a symptom
    of one hot replica: every replica enforces the same quota map, so a
    multi-endpoint client must NOT fail over (it would just burn the
    other replicas' admission budget re-refusing the same tenant).
    Back off and retry later, or shed load at the source."""

    wire_code = "tenant_quota"


#: wire code → exception class, for the client side of the envelope.
WIRE_CODES = {
    cls.wire_code: cls
    for cls in (RetryableElsewhere, OverloadedError, DrainingError,
                NotLeaderError, ClusterLostError, TenantQuotaError)
}


def decorrelated_jitter(
    rng: random.Random, base: float, prev: float | None, cap: float
) -> float:
    """One step of capped decorrelated-jitter backoff.

    ``prev=None`` (first failure) yields a delay in ``[base, base * 3]``;
    afterwards ``[base, prev * 3]``, always clamped to ``[base, cap]``.
    """
    upper = max(base, (base if prev is None else prev) * 3.0)
    return min(cap, rng.uniform(base, upper))


class RetryPolicy:
    """Bounded retries with capped decorrelated-jitter backoff.

    Pure decision object: it computes delays and classifies errors but
    never sleeps or catches anything itself, so callers keep control of
    their deadline accounting (see :meth:`CapacityClient.call
    <..service.client.CapacityClient.call>`).  Thread-safe: concurrent
    callers share the seeded RNG under a lock.
    """

    #: Error families that indicate a broken transport (worth a retry on
    #: an idempotent op) rather than a deterministic application error.
    TRANSPORT_ERRORS: tuple[type[BaseException], ...] = (OSError,)

    def __init__(
        self,
        *,
        max_attempts: int = 3,
        base_delay_s: float = 0.05,
        max_delay_s: float = 2.0,
        seed: int | None = None,
    ) -> None:
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
        if base_delay_s <= 0 or max_delay_s < base_delay_s:
            raise ValueError(
                "need 0 < base_delay_s <= max_delay_s, got "
                f"{base_delay_s}/{max_delay_s}"
            )
        self.max_attempts = int(max_attempts)
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def next_delay(self, prev: float | None = None) -> float:
        """The delay before the next attempt, given the previous delay
        (``None`` for the first retry)."""
        with self._lock:
            return decorrelated_jitter(
                self._rng, self.base_delay_s, prev, self.max_delay_s
            )

    @staticmethod
    def is_transport_error(exc: BaseException) -> bool:
        """Retryable = the transport broke (socket/OS-level, or the
        protocol layer's framing error).  Application errors — the server
        answered ``ok: false`` — are deterministic and never retryable."""
        from kubernetesclustercapacity_tpu.service.protocol import (
            ProtocolError,
        )

        if isinstance(exc, DeadlineExpired):
            # A spent budget is the CALLER's condition, not the wire's —
            # retrying cannot un-spend it (and TimeoutError would
            # otherwise ride the OSError branch).
            return False
        return isinstance(exc, (OSError, ProtocolError))


class Deadline:
    """An absolute time budget, threaded through protocol messages.

    Carried on the wire as an absolute unix timestamp (``time.time()``
    epoch seconds) so the server can shed requests whose caller already
    gave up instead of burning a kernel dispatch on them.  Same-host
    deployments (the localhost-bench default) share a clock exactly;
    cross-host callers should keep budgets comfortably above their NTP
    skew — a shed is an *optimization*, the client's own budget check is
    authoritative either way.
    """

    __slots__ = ("_at",)

    def __init__(self, at: float) -> None:
        self._at = float(at)

    @classmethod
    def after(cls, timeout_s: float) -> "Deadline":
        """A deadline ``timeout_s`` seconds from now."""
        return cls(time.time() + float(timeout_s))

    @classmethod
    def from_wire(cls, value) -> "Deadline":
        """Parse the wire form (a JSON number); raises ValueError on
        anything else so a malformed field is a request error, not a
        silent no-deadline."""
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise ValueError(
                f"deadline must be a unix timestamp number, got {value!r}"
            )
        return cls(float(value))

    def to_wire(self) -> float:
        return self._at

    def remaining(self) -> float:
        """Seconds left (negative once expired)."""
        return self._at - time.time()

    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def __repr__(self) -> str:  # debugging/log lines
        return f"Deadline(remaining={self.remaining():.3f}s)"


class CircuitBreaker:
    """Thread-safe closed / open / half-open circuit breaker.

    * **closed** — calls flow; ``failure_threshold`` *consecutive*
      failures trip it open.
    * **open** — :meth:`allow` refuses everything until
      ``recovery_timeout_s`` has elapsed.  ``recovery_timeout_s=None``
      means the breaker stays open until an explicit :meth:`reset` —
      the right shape for deterministic per-process failures like a
      kernel that will not compile on this chip.
    * **half-open** — after the cooldown, up to ``half_open_max_calls``
      probe calls are admitted; one success closes the breaker, one
      failure re-opens it (and restarts the cooldown).

    All transitions happen under one lock; ``clock`` is injectable for
    tests (monotonic seconds).  ``on_state_change(old, new)`` is an
    optional observer fired AFTER the lock is released on every state
    transition (telemetry counters hang here — see
    :mod:`.ops.pallas_fit`); a raising observer is swallowed, since a
    metrics hook must never change breaker behavior.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(
        self,
        *,
        failure_threshold: int = 5,
        recovery_timeout_s: float | None = 30.0,
        half_open_max_calls: int = 1,
        name: str = "",
        clock=time.monotonic,
        on_state_change=None,
    ) -> None:
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}"
            )
        if half_open_max_calls < 1:
            raise ValueError(
                f"half_open_max_calls must be >= 1, got {half_open_max_calls}"
            )
        self.name = name
        self._on_state_change = on_state_change
        self._threshold = int(failure_threshold)
        self._recovery = (
            None if recovery_timeout_s is None else float(recovery_timeout_s)
        )
        self._half_open_max = int(half_open_max_calls)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._consecutive_failures = 0
        self._opened_at: float | None = None
        self._half_open_inflight = 0
        self._last_error: str | None = None
        # Lifetime counters (monotonic; surfaced via snapshot()).
        self._trips = 0
        self._successes = 0
        self._failures = 0
        self._rejected = 0

    # -- decisions ---------------------------------------------------------
    def allow(self) -> bool:
        """May a call proceed right now?  (Open→half-open transitions
        happen here, when the cooldown elapses.)"""
        transition = None
        with self._lock:
            if self._state == self.CLOSED:
                return True
            if self._state == self.OPEN:
                if (
                    self._recovery is not None
                    and self._opened_at is not None
                    and self._clock() - self._opened_at >= self._recovery
                ):
                    self._state = self.HALF_OPEN
                    self._half_open_inflight = 0
                    transition = (self.OPEN, self.HALF_OPEN)
                else:
                    self._rejected += 1
                    return False
            # HALF_OPEN: admit a bounded number of probes.
            if self._half_open_inflight < self._half_open_max:
                self._half_open_inflight += 1
                admitted = True
            else:
                self._rejected += 1
                admitted = False
        if transition is not None:
            self._notify(*transition)
        return admitted

    def call(self, fn, *args, **kwargs):
        """Run ``fn`` under the breaker: refuse with
        :class:`CircuitOpenError` when open, record the outcome
        otherwise.  Exceptions from ``fn`` count as failures and
        propagate unchanged."""
        if not self.allow():
            with self._lock:
                # Snapshot once, under the lock: two lock-free reads
                # could see different values (checked one error, printed
                # another) when a probe thread races record_success.
                last_error = self._last_error
            raise CircuitOpenError(
                f"circuit breaker {self.name or id(self)} is open"
                + (f" (last error: {last_error})" if last_error else "")
            )
        try:
            result = fn(*args, **kwargs)
        except Exception as e:
            self.record_failure(f"{type(e).__name__}: {e}")
            raise
        self.record_success()
        return result

    # -- outcomes ----------------------------------------------------------
    def record_success(self) -> None:
        transition = None
        with self._lock:
            self._successes += 1
            self._consecutive_failures = 0
            self._last_error = None
            if self._state == self.HALF_OPEN:
                # One healthy probe closes the circuit.
                self._state = self.CLOSED
                self._half_open_inflight = 0
                self._opened_at = None
                transition = (self.HALF_OPEN, self.CLOSED)
            elif self._state == self.OPEN:
                # A success recorded while open (caller raced the trip):
                # evidence the dependency works — close.
                self._state = self.CLOSED
                self._opened_at = None
                transition = (self.OPEN, self.CLOSED)
        if transition is not None:
            self._notify(*transition)

    def record_failure(self, error: str | None = None) -> None:
        transition = None
        with self._lock:
            self._failures += 1
            self._consecutive_failures += 1
            if error is not None:
                self._last_error = error
            if self._state == self.HALF_OPEN:
                # The probe failed: straight back to open, cooldown restarts.
                transition = (self.HALF_OPEN, self.OPEN)
                self._trip_locked()
            elif (
                self._state == self.CLOSED
                and self._consecutive_failures >= self._threshold
            ):
                transition = (self.CLOSED, self.OPEN)
                self._trip_locked()
        if transition is not None:
            self._notify(*transition)

    def _trip_locked(self) -> None:
        self._state = self.OPEN
        self._opened_at = self._clock()
        self._half_open_inflight = 0
        self._trips += 1

    def _notify(self, old: str, new: str) -> None:
        """Fire the transition observer — outside the lock (it may take
        its own, e.g. a metrics registry's), never allowed to raise."""
        if self._on_state_change is None:
            return
        try:
            self._on_state_change(old, new)
        except Exception:  # noqa: BLE001 - observers must not change behavior
            pass

    def reset(self) -> None:
        """Force-close and clear the error (operator/tests re-arm)."""
        with self._lock:
            old = self._state
            self._state = self.CLOSED
            self._consecutive_failures = 0
            self._opened_at = None
            self._half_open_inflight = 0
            self._last_error = None
        if old != self.CLOSED:
            self._notify(old, self.CLOSED)

    # -- observability -----------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            # Report half-open once the cooldown has lapsed even if no
            # probe has arrived yet — observers see what the next call
            # would experience.
            if (
                self._state == self.OPEN
                and self._recovery is not None
                and self._opened_at is not None
                and self._clock() - self._opened_at >= self._recovery
            ):
                return self.HALF_OPEN
            return self._state

    @property
    def last_error(self) -> str | None:
        with self._lock:
            return self._last_error

    def snapshot(self) -> dict:
        """Counters for the ``info`` op / doctor: pure data, no locks
        held by the caller afterwards."""
        state = self.state  # takes the lock; computes lapsed-cooldown view
        with self._lock:
            return {
                "state": state,
                "consecutive_failures": self._consecutive_failures,
                "failures": self._failures,
                "successes": self._successes,
                "trips": self._trips,
                "rejected": self._rejected,
                "last_error": self._last_error,
            }


class TokenBucket:
    """Thread-safe token bucket: ``rate_per_s`` tokens/second of refill
    up to ``capacity`` (the burst bound), starting full.

    The rps half of server admission control: one :meth:`try_acquire`
    per request; a request that finds the bucket empty is shed with
    :class:`OverloadedError` instead of queued (the concurrency limiter
    owns the queue; stacking a second queue here would just hide the
    overload behind latency).  Non-blocking by design — the refill is
    computed lazily from the injectable monotonic ``clock``, so there is
    no filler thread to leak and the arithmetic is exactly testable
    against an offline oracle (``tests/test_plane.py`` pins it against
    a numpy recurrence).
    """

    def __init__(
        self,
        rate_per_s: float,
        capacity: float | None = None,
        *,
        clock=time.monotonic,
    ) -> None:
        if rate_per_s <= 0:
            raise ValueError(f"rate_per_s must be > 0, got {rate_per_s}")
        if capacity is None:
            capacity = max(float(rate_per_s), 1.0)
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.rate_per_s = float(rate_per_s)
        self.capacity = float(capacity)
        self._clock = clock
        self._lock = threading.Lock()
        self._tokens = self.capacity
        self._last = clock()

    def _refill_locked(self) -> None:
        now = self._clock()
        elapsed = now - self._last
        self._last = now
        if elapsed > 0:
            self._tokens = min(
                self.capacity, self._tokens + elapsed * self.rate_per_s
            )

    def try_acquire(self, tokens: float = 1.0) -> bool:
        """Take ``tokens`` if available right now; never blocks."""
        if tokens <= 0:
            raise ValueError(f"tokens must be > 0, got {tokens}")
        with self._lock:
            self._refill_locked()
            if self._tokens >= tokens:
                self._tokens -= tokens
                return True
            return False

    def available(self) -> float:
        """Current token count after refill (observability/tests)."""
        with self._lock:
            self._refill_locked()
            return self._tokens
