"""Environment diagnostics: a hang-proof report of the stack's health.

The accelerator here can sit behind a tunnel whose PJRT init hangs
*indefinitely* (it cost two benchmark rounds their numbers): any probe
of ``jax.devices()`` therefore runs in a KILLED-ON-TIMEOUT subprocess,
never in the caller's process — a stuck init can only be recovered by
killing the process that attempted it, and the doctor must never become
the thing it diagnoses.

Surfaced via ``kccap -doctor`` (``cli.py``).  The reference has no
equivalent; this exists because a live-cluster tool whose backend can
wedge needs a first-line triage command.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

__all__ = ["run_doctor", "doctor_report", "healthy"]

# The probe child's entire program: stdlib + jax only, so a hang here
# indicts the environment, not this package (same discrimination logic
# as bench.py's probe child).
_PROBE_CODE = """\
import time
t0 = time.time()
import jax
d = jax.devices()
print("DEVICES %.1fs %s x%d" % (time.time() - t0, d[0], len(d)), flush=True)
"""


def _probe_backend(timeout_s: float, probe_code: str = _PROBE_CODE) -> str:
    """Run the jax.devices() probe in a killable child; never hangs.

    Output is read by a pump thread, not ``communicate()``: on this
    path (single merged pipe + text mode + timeout) CPython's
    retry-without-loss guarantee proved unreliable — partial output
    written before the hang vanished, and that partial output is
    exactly the diagnostic a wedged-init report needs.
    """
    import threading

    proc = subprocess.Popen(
        [sys.executable, "-c", probe_code],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        start_new_session=True,
    )
    lines: list[str] = []

    def pump() -> None:
        assert proc.stdout is not None
        for line in proc.stdout:
            lines.append(line.rstrip("\n"))

    # kccap: lint-ok[hygiene-thread-death] pump lifetime is bounded by reader.join(timeout); a late death only truncates probe output, never the report
    reader = threading.Thread(target=pump, daemon=True)
    reader.start()
    try:
        proc.wait(timeout=timeout_s)
        hung = False
    except subprocess.TimeoutExpired:
        hung = True
        # Whole-group SIGKILL: PJRT spawns threads that ignore SIGTERM
        # while blocked in C++ (same rationale as bench.py::_kill_group —
        # kept in lockstep by hand; bench's parent may not import this
        # package, whose __init__ pulls in jax).
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError, OSError):
            pass
        try:
            proc.wait(timeout=10)
        except Exception:  # noqa: BLE001 - best-effort reap
            pass
    reader.join(timeout=5)  # EOF follows the kill; bounded regardless
    if proc.stdout is not None:
        proc.stdout.close()
    if hung:
        tail = [ln for ln in lines if ln][-2:]
        return (
            f"HUNG: backend init did not return within {timeout_s:.0f}s "
            "(killed) — the accelerator plugin/tunnel is wedged; CPU "
            "surfaces (-backend native, packing, store) still work"
            + (f" | last output: {' | '.join(tail)}" if tail else "")
        )
    for line in lines:
        if line.startswith("DEVICES"):
            return "ok: " + line[len("DEVICES "):]
    tail = [ln for ln in lines if ln][-3:]
    return "FAILED: " + (" | ".join(tail) if tail else "no output")


def doctor_report(
    *,
    backend_timeout_s: float = 30.0,
    probe_code: str | None = None,
    service_addr: tuple[str, int] | None = None,
    federation_addr: tuple[str, int] | None = None,
) -> list[tuple[str, str]]:
    """Collect (check, result) pairs.  Pure data; rendering is the CLI's.

    ``probe_code`` defaults to the module's probe at CALL time (not def
    time) so tests can swap ``_PROBE_CODE`` without re-binding defaults.
    """
    if probe_code is None:
        probe_code = _PROBE_CODE
    checks: list[tuple[str, str]] = []

    def check(name: str, fn) -> None:
        # One broken subsystem must become a FAILED line, never abort the
        # report — broken environments are exactly what -doctor triages,
        # and the backend probe's result must survive whatever follows.
        try:
            checks.append((name, fn()))
        except Exception as e:  # noqa: BLE001 - diagnostic must complete
            checks.append((name, f"FAILED: {type(e).__name__}: {e}"))

    def _pkg():
        import kubernetesclustercapacity_tpu as kcc

        return f"kubernetesclustercapacity_tpu {kcc.__version__}"

    check("package", _pkg)
    check(
        "platform env",
        lambda: os.environ.get("JAX_PLATFORMS", "(default)"),
    )
    check(
        "backend probe",
        lambda: _probe_backend(backend_timeout_s, probe_code),
    )

    def _x64():
        # In-process jax state: config only — never touches a backend.
        import jax

        return ("ok" if jax.config.jax_enable_x64 else
                "DISABLED — int64 Go-semantics kernels need jax_enable_x64")

    check("x64 ints", _x64)

    def _native():
        from kubernetesclustercapacity_tpu import native as _ncap

        return ("ok: compiled" if _ncap.available() else
                "unavailable (g++ missing or build failed) — "
                "-backend native off")

    check("native kernel (C++)", _native)

    def _walk():
        from kubernetesclustercapacity_tpu.native import ingest as _ingest

        return ("ok: compiled" if _ingest.available() else
                "unavailable — packers use the pure-Python walk")

    check("native pod-walk (C ext)", _walk)

    def _fast():
        from kubernetesclustercapacity_tpu.ops.pallas_fit import (
            fast_path_breaker_snapshot,
            fast_path_error,
        )

        b = fast_path_breaker_snapshot()
        err = fast_path_error() or b["last_error"]
        if b["state"] != "closed" or err:
            return (
                f"degraded: breaker {b['state']}, trips={b['trips']}, "
                f"rejected={b['rejected']}"
                + (f" — {err}" if err else "")
            )
        return (
            "armed (trips only on failure; breaker closed, "
            f"successes={b['successes']})"
        )

    check("fused fast path", _fast)

    def _telemetry():
        # The process registry + one exposition render: proves the
        # scrape surface works in THIS environment (and how big it is)
        # without binding a port.
        from kubernetesclustercapacity_tpu.telemetry.exposition import (
            render_text,
        )
        from kubernetesclustercapacity_tpu.telemetry.metrics import (
            REGISTRY,
            enabled,
        )

        if not enabled():
            return "disabled (KCCAP_TELEMETRY=0) — registry calls off"
        families = REGISTRY.collect()
        text = render_text(REGISTRY)
        return (
            f"ok: {len(families)} metric families, exposition renders "
            f"{len(text)} bytes"
        )

    check("telemetry", _telemetry)

    def _hot_path():
        # The process device cache + bucket ladder: hit rates say whether
        # repeat sweeps are actually reusing device-resident arrays, and
        # the floor says which shape bucket small clusters share.
        from kubernetesclustercapacity_tpu import devcache

        if not devcache.enabled():
            return (
                "disabled (KCCAP_DEVCACHE=0) — per-request device "
                "uploads, no shape bucketing"
            )
        st = devcache.CACHE.stats()
        return (
            f"ok: {st['entries']} entries, hits={st['hits']} "
            f"misses={st['misses']} hit_rate={st['hit_rate']:.2f}, "
            f"node bucket floor {devcache.node_bucket_floor()}"
        )

    check("device snapshot cache", _hot_path)

    def _sanitizer():
        # The concurrency-certification gate: is the dynamic sanitizer
        # armed in THIS process, and has any supervised worker died?
        # (No probe run here — the hammer lives in tier-1/CLI; the
        # doctor reports the standing state an operator can act on.)
        from kubernetesclustercapacity_tpu.analysis import sanitize
        from kubernetesclustercapacity_tpu.utils import threads as _threads

        deaths = _threads.death_count()
        death_note = ""
        if deaths:
            name, err = _threads.last_death()
            death_note = (
                f"; WARNING {deaths} supervised thread death(s), "
                f"last: {name}: {err}"
            )
        if sanitize.installed():
            st = sanitize.stats()
            return (
                f"INSTALLED: seed {st['seed']}, "
                f"{st['instrumented_classes']} class(es) instrumented, "
                f"{st['races']} race(s) observed — a serving process "
                "should never run instrumented" + death_note
            )
        if sanitize.enabled():
            return (
                "armed (KCCAP_SANITIZE=1): instrumentation installs on "
                "demand; run kccap-sanitize for the seeded hammer"
                + death_note
            )
        return (
            "dormant (KCCAP_SANITIZE unset) — zero instrumentation; "
            "races/lock-order are certified by the tier-1 hammer"
            + death_note
        )

    check("sanitizer", _sanitizer)

    def _profiler():
        # The continuous profiler's standing state: armed/sampling/off.
        # Off is soft (a configuration, not a failure); a profiler whose
        # supervised sampler died shows up in the sanitizer line's
        # thread-death note.
        from kubernetesclustercapacity_tpu.telemetry.profiler import (
            profiler_status,
        )

        return profiler_status()

    check("profiler", _profiler)

    def _device_memory():
        # The device-memory book: live/peak staged bytes and the leak
        # alert.  A sustained reconcile discrepancy or a breached HBM
        # budget is a hard FAILED line — silent device leaks are the
        # incident class the ledger exists to make impossible.
        from kubernetesclustercapacity_tpu.telemetry.memledger import (
            device_memory_status,
            enabled as _ledger_enabled,
        )
        from kubernetesclustercapacity_tpu.telemetry.memledger import (
            LEDGER,
        )

        if _ledger_enabled():
            # In-process reconcile against jax.live_arrays(): config
            # state only when jax never initialized a backend here.
            import sys as _sys

            if "jax" in _sys.modules:
                try:
                    LEDGER.reconcile()
                except Exception:  # noqa: BLE001 - audit must not abort
                    pass
        return device_memory_status()

    check("device memory", _device_memory)

    def _optimizer():
        # One tiny certified solve in-process: proves the LP/PDHG
        # backend converges AND certifies on this host — an optimizer
        # that cannot close its duality gap is a hard FAILED line (its
        # bounds would be valid but useless).
        import numpy as _np

        from kubernetesclustercapacity_tpu.optimize import (
            optimize_snapshot,
        )
        from kubernetesclustercapacity_tpu.scenario import ScenarioGrid
        from kubernetesclustercapacity_tpu.snapshot import (
            synthetic_snapshot,
        )

        snap = synthetic_snapshot(64, seed=3, shapes=4)
        grid = ScenarioGrid(
            cpu_request_milli=_np.array([250, 2000], dtype=_np.int64),
            mem_request_bytes=_np.array(
                [256 << 20, 2 << 30], dtype=_np.int64
            ),
            replicas=_np.array([10**6, 3], dtype=_np.int64),
        )
        r = optimize_snapshot(snap, grid, mode="strict")
        if not r.all_certified:
            return (
                "FAILED: uncertified solve — worst gap "
                f"{float(r.duality_gap.max()):.2e} after "
                f"{r.iterations} iteration(s) (tol {r.tol})"
            )
        if r.verified is not None and not bool(r.verified.all()):
            return "FAILED: rounded packing failed oracle verification"
        return (
            f"ok: certified in {r.iterations} iteration(s), worst gap "
            f"{float(r.duality_gap.max()):.1e}, bound "
            f"{float(r.lp_bound[0]):.1f} vs rounded "
            f"{int(r.rounded[0])}"
        )

    check("optimizer", _optimizer)

    if service_addr is not None:
        # A LIVE service's resilience counters (deadline sheds, breaker
        # state, follower retry/backoff) — the doctor probes the same
        # info op clients use, with a short budget so a wedged server
        # cannot hang the report.
        def _service():
            from kubernetesclustercapacity_tpu.resilience import RetryPolicy
            from kubernetesclustercapacity_tpu.service.client import (
                CapacityClient,
            )

            with CapacityClient(
                *service_addr,
                connect_timeout_s=5.0,
                timeout_s=5.0,
                retry=RetryPolicy(max_attempts=2, base_delay_s=0.1),
                deadline_s=5.0,
            ) as c:
                info = c.info(metrics=True, hot_path=True)
            r = info.get("resilience", {})
            fp = r.get("fast_path_breaker", {})
            parts = [
                f"ok: {info.get('nodes')} nodes ({info.get('semantics')})",
                f"deadline_shed={r.get('deadline_shed')}",
                f"fast_path={fp.get('state')}",
            ]
            hp = info.get("hot_path") or {}
            dc = hp.get("devcache")
            if dc:
                parts.append(
                    f"devcache_hit_rate={dc.get('hit_rate', 0):.2f}"
                )
            bt = hp.get("batching")
            if bt:
                parts.append(
                    f"mean_batch={bt.get('mean_batch_size', 0):.2f}"
                )
            reqs = (
                info.get("metrics", {})
                .get("kccap_requests_total", {})
                .get("values", {})
            )
            if reqs:
                parts.append(f"requests={int(sum(reqs.values()))}")
            follower = r.get("follower")
            if follower:
                parts.append(
                    "follower relists=%s watch_failures=%s backoff=%s"
                    % (
                        follower.get("relists"),
                        follower.get("watch_failures"),
                        follower.get("backoff_s") or "none",
                    )
                )
            return " ".join(parts)

        check("capacity service", _service)

        # Multi-tenancy: is a tenant map armed, how many tenants, who
        # is being shed.  A server without -tenants reports a soft
        # "off" line (single-tenant deployments are the default, not a
        # failure).  Separate connection for the usual isolation reason.
        def _tenancy():
            from kubernetesclustercapacity_tpu.resilience import RetryPolicy
            from kubernetesclustercapacity_tpu.service.client import (
                CapacityClient,
            )

            with CapacityClient(
                *service_addr,
                connect_timeout_s=5.0,
                timeout_s=5.0,
                retry=RetryPolicy(max_attempts=2, base_delay_s=0.1),
                deadline_s=5.0,
            ) as c:
                info = c.info(tenancy=True)
            caps = info.get("capabilities") or {}
            ten = info.get("tenancy")
            if not caps.get("tenancy") or not isinstance(ten, dict):
                return "off (no -tenants map; single-tenant admission)"
            # info's "tenants" key carries TenantMap.to_wire(), which
            # nests the spec list under its own "tenants" key.
            tmap = ten.get("tenants") or {}
            specs = tmap.get("tenants") or [] if isinstance(
                tmap, dict
            ) else tmap
            parts = [f"ok: {len(specs)} tenant(s)"]
            adm = ten.get("admission")
            if isinstance(adm, dict):
                active = adm.get("active") or {}
                shed = adm.get("shed") or {}
                if active:
                    parts.append(
                        "active="
                        + ",".join(
                            f"{t}:{n}" for t, n in sorted(active.items())
                        )
                    )
                total_shed = sum(shed.values()) if shed else 0
                parts.append(f"tenant_shed={total_shed}")
                fq = adm.get("fair_queue")
                if isinstance(fq, dict):
                    parts.append(
                        f"fair_queue={fq.get('free')}/{fq.get('slots')} free"
                        f" waiting={fq.get('waiting')}"
                    )
            return " ".join(parts)

        check("tenancy", _tenancy)

        # The service's capacity timeline: generation history + watch
        # alert states — the "did capacity drift while nobody looked"
        # line.  Same short budgets; separate connection so a timeline
        # failure cannot contaminate the lines above.
        def _timeline():
            from kubernetesclustercapacity_tpu.resilience import RetryPolicy
            from kubernetesclustercapacity_tpu.service.client import (
                CapacityClient,
            )

            with CapacityClient(
                *service_addr,
                connect_timeout_s=5.0,
                timeout_s=5.0,
                retry=RetryPolicy(max_attempts=2, base_delay_s=0.1),
                deadline_s=5.0,
            ) as c:
                t = c.timeline()
            if not t.get("enabled", False):
                return "not configured (-watch / -timeline-depth off)"
            parts = [
                f"ok: {t.get('count')}/{t.get('depth')} generations",
                f"generation={t.get('generation')}",
                f"watches={len(t.get('watchlist', []))}",
            ]
            alerts = t.get("alerts", {})
            flagged = [
                f"{name}={a['state']}(breaches={a['breaches']})"
                for name, a in sorted(alerts.items())
                if a.get("state") != "ok"
            ]
            if flagged:
                parts.append("alerts: " + " ".join(flagged))
            elif alerts:
                parts.append("alerts: all ok")
            return " ".join(parts)

        check("capacity timeline", _timeline)

        # The service's capacity-at-risk watches: the last quantile
        # capacities and their alert states.  A breached quantile watch
        # is a hard FAILED line — it is a standing confidence statement
        # ("with 95% confidence fewer than N replicas fit") that the
        # cluster no longer meets, the stochastic analog of a breached
        # SLO.  Same short budgets; separate connection so a car-op
        # failure cannot contaminate the timeline line above.
        def _car():
            from kubernetesclustercapacity_tpu.resilience import RetryPolicy
            from kubernetesclustercapacity_tpu.service.client import (
                CapacityClient,
            )

            with CapacityClient(
                *service_addr,
                connect_timeout_s=5.0,
                timeout_s=5.0,
                retry=RetryPolicy(max_attempts=2, base_delay_s=0.1),
                deadline_s=5.0,
            ) as c:
                status = c.car()
            if not status.get("enabled", False):
                return "not configured (no quantile: watches in -watch)"
            parts = []
            for name in sorted(status.get("watches", {})):
                w = status["watches"][name]
                parts.append(
                    f"{name}=p{w['quantile'] * 100:g}:"
                    f"{w.get('last_total')}"
                    f"(pfit={w.get('prob_fit')},"
                    f"{w['alert']['state']})"
                )
            breached = status.get("breached", [])
            if breached:
                return (
                    "FAILED: capacity-at-risk breach — "
                    + ", ".join(breached)
                    + " below min_replicas at their quantile; "
                    + " ".join(parts)
                )
            return "ok: " + " ".join(parts)

        check("capacity at risk", _car)

        # The service's gang watches: the last whole-gang counts and
        # their alert states.  A breached gang watch is a hard FAILED
        # line — "fewer than N whole gangs fit" is the all-or-nothing
        # capacity statement a training-job admission plane relies on,
        # the gang analog of a breached quantile watch.  Same short
        # budgets; separate connection so a gang-op failure cannot
        # contaminate the lines above.
        def _gang():
            from kubernetesclustercapacity_tpu.resilience import RetryPolicy
            from kubernetesclustercapacity_tpu.service.client import (
                CapacityClient,
            )

            with CapacityClient(
                *service_addr,
                connect_timeout_s=5.0,
                timeout_s=5.0,
                retry=RetryPolicy(max_attempts=2, base_delay_s=0.1),
                deadline_s=5.0,
            ) as c:
                status = c.gang()
            if not status.get("enabled", False):
                return "not configured (no gang: watches in -watch)"
            parts = []
            for name in sorted(status.get("watches", {})):
                w = status["watches"][name]
                parts.append(
                    f"{name}={w.get('last_gangs')}x{w['ranks']}rank"
                    f"({w.get('binding')},{w['alert']['state']})"
                )
            breached = status.get("breached", [])
            if breached:
                return (
                    "FAILED: gang capacity breach — "
                    + ", ".join(breached)
                    + " below min_replicas whole gangs; "
                    + " ".join(parts)
                )
            return "ok: " + " ".join(parts)

        check("gang capacity", _gang)

        # The service's forecast (horizon) watches: the projected
        # quantile minimum over each watch's horizon and the
        # time-to-breach.  A breached horizon watch is a hard FAILED
        # line — "the p95 capacity crosses the threshold within the
        # horizon" is the early-warning statement an autoscaler plans
        # against, and it fires BEFORE the plain quantile watch does.
        # Same short budgets; separate connection so a forecast-op
        # failure cannot contaminate the lines above.
        def _forecast():
            from kubernetesclustercapacity_tpu.resilience import RetryPolicy
            from kubernetesclustercapacity_tpu.service.client import (
                CapacityClient,
            )

            with CapacityClient(
                *service_addr,
                connect_timeout_s=5.0,
                timeout_s=5.0,
                retry=RetryPolicy(max_attempts=2, base_delay_s=0.1),
                deadline_s=5.0,
            ) as c:
                status = c.forecast()
            if not status.get("enabled", False):
                return "not configured (no horizon: watches in -watch)"
            parts = []
            for name in sorted(status.get("watches", {})):
                w = status["watches"][name]
                ttb = w.get("time_to_breach_s")
                parts.append(
                    f"{name}=p{w['quantile'] * 100:g}:"
                    f"min{w.get('horizon_min_capacity')}"
                    f"(ttb={'-' if ttb is None else f'{ttb:g}s'},"
                    f"{w['alert']['state']})"
                )
            breached = status.get("breached", [])
            if breached:
                return (
                    "FAILED: forecast breach — "
                    + ", ".join(breached)
                    + " projected below min_replicas within their "
                    "horizon; " + " ".join(parts)
                )
            return "ok: " + " ".join(parts)

        check("capacity forecast", _forecast)

        # The service's audit log + shadow oracle: is correctness being
        # continuously observed, and has it ever been caught lying?  A
        # recorded divergence is a hard FAILED line — it means a served
        # answer disagreed with the sequential oracle in production,
        # which is exactly the incident this check exists to surface.
        def _audit_shadow():
            from kubernetesclustercapacity_tpu.resilience import RetryPolicy
            from kubernetesclustercapacity_tpu.service.client import (
                CapacityClient,
            )

            with CapacityClient(
                *service_addr,
                connect_timeout_s=5.0,
                timeout_s=5.0,
                retry=RetryPolicy(max_attempts=2, base_delay_s=0.1),
                deadline_s=5.0,
            ) as c:
                a = c.audit_status()
            if not a.get("enabled", False):
                return (
                    "not configured (-audit-dir / -shadow-sample-rate off)"
                )
            parts = []
            log = a.get("log")
            if log:
                parts.append(
                    f"audit: {log['records']} record(s) in "
                    f"{log['segments']} segment(s), "
                    f"generation={log['last_generation']}"
                )
            sh = a.get("shadow")
            if sh:
                parts.append(
                    f"shadow: rate={sh['sample_rate']} "
                    f"checked={sh['checked']} "
                    f"divergences={sh['divergences']} "
                    f"state={sh['alert']['state']}"
                )
                if sh["divergences"]:
                    return (
                        "FAILED: shadow-oracle divergence — served "
                        "answers disagreed with the oracle; "
                        + " ".join(parts)
                    )
            return "ok: " + " ".join(parts)

        check("audit & shadow", _audit_shadow)

        # The service's own latency + SLO burn-rate state: p50/p99 of
        # its request-latency histogram (estimated from the scrape's
        # buckets) and every -slo objective's alert state.  A breached
        # objective is a hard FAILED line — the service is burning its
        # error budget faster than the page threshold RIGHT NOW.
        def _latency_slo():
            from kubernetesclustercapacity_tpu.resilience import RetryPolicy
            from kubernetesclustercapacity_tpu.service.client import (
                CapacityClient,
            )
            from kubernetesclustercapacity_tpu.telemetry.slo import (
                estimate_quantile,
            )

            with CapacityClient(
                *service_addr,
                connect_timeout_s=5.0,
                timeout_s=5.0,
                retry=RetryPolicy(max_attempts=2, base_delay_s=0.1),
                deadline_s=5.0,
            ) as c:
                slo = c.slo_status()
                info = c.info(metrics=True)
            parts = []
            lat = (
                info.get("metrics", {})
                .get("kccap_request_latency_seconds", {})
                .get("values", {})
            )
            # Pool every op's buckets into one overall latency estimate
            # (cumulative dicts share boundaries by construction).
            pooled: dict[str, int] = {}
            count = 0
            for hist in lat.values():
                count += hist.get("count", 0)
                for le, cum in hist.get("buckets", {}).items():
                    pooled[le] = pooled.get(le, 0) + cum
            if count:
                p50 = estimate_quantile(pooled, count, 0.50)
                p99 = estimate_quantile(pooled, count, 0.99)
                parts.append(
                    f"latency p50={p50 * 1e3:.1f}ms "
                    f"p99={p99 * 1e3:.1f}ms over {count} request(s)"
                )
            if not slo.get("enabled", False):
                parts.append("slo: not configured (-slo off)")
                return "ok: " + " ".join(parts)
            states = []
            breached = []
            for name in sorted(slo.get("status", {})):
                s = slo["status"][name]
                states.append(f"{name}={s['state']}")
                if s["state"] == "breached":
                    breached.append(
                        f"{name} ({s['objective']}, "
                        f"short={s['short_burn']:.1f}x "
                        f"long={s['long_burn']:.1f}x)"
                    )
            parts.append("slo: " + " ".join(states))
            if breached:
                return (
                    "FAILED: error budget fast-burning — "
                    + "; ".join(breached) + "; " + " ".join(parts)
                )
            return "ok: " + " ".join(parts)

        check("latency & SLO", _latency_slo)

        # The service's flight recorder: its last-K request history over
        # the dump op — one line of "what was this server just doing"
        # before anyone attaches a debugger.  Same short budgets as the
        # info probe; separate connection so a dump-op failure cannot
        # contaminate the resilience line above.
        def _flight():
            from kubernetesclustercapacity_tpu.resilience import RetryPolicy
            from kubernetesclustercapacity_tpu.service.client import (
                CapacityClient,
            )

            with CapacityClient(
                *service_addr,
                connect_timeout_s=5.0,
                timeout_s=5.0,
                retry=RetryPolicy(max_attempts=2, base_delay_s=0.1),
                deadline_s=5.0,
            ) as c:
                dump = c.dump()
            records = dump.get("records", [])
            parts = [
                f"ok: {dump.get('count')}/{dump.get('capacity')} records",
                f"generation={dump.get('generation')}",
                f"dropped={dump.get('dropped')}",
            ]
            errors = sum(1 for r in records if r.get("status") == "error")
            if errors:
                parts.append(f"errors={errors}")
            if records:
                last = records[-1]
                parts.append(
                    f"last={last.get('op')}/{last.get('status')} "
                    f"{last.get('latency_ms')}ms"
                )
            return " ".join(parts)

        check("flight recorder", _flight)

        # Tracing posture: is the server emitting spans at all, what
        # tail-sampling policy gates the bodies, and is the ring
        # shedding (dropped spans mean traces are losing limbs under
        # load — raise max_spans or tighten the sample spec).
        def _tracing():
            from kubernetesclustercapacity_tpu.resilience import RetryPolicy
            from kubernetesclustercapacity_tpu.service.client import (
                CapacityClient,
            )

            with CapacityClient(
                *service_addr,
                connect_timeout_s=5.0,
                timeout_s=5.0,
                retry=RetryPolicy(max_attempts=2, base_delay_s=0.1),
                deadline_s=5.0,
            ) as c:
                tr = c.info(tracing=True).get("tracing", {})
            if not tr.get("armed", False):
                return (
                    "not configured (-trace-log off"
                    + (
                        "; request log armed"
                        if tr.get("request_log")
                        else ""
                    )
                    + ")"
                )
            parts = [
                f"ok: sample={tr.get('spec')}",
                f"buffered={tr.get('buffered_traces')}",
                f"kept={tr.get('kept_spans')}",
            ]
            dropped = tr.get("dropped_spans", 0)
            if dropped:
                parts.append(f"dropped={dropped} (ring shedding)")
            return " ".join(parts)

        check("tracing", _tracing)

    if federation_addr is not None:
        # The federation tier's degradation vector: which clusters are
        # fresh, which serve explicitly-stale views, and which are LOST.
        # A lost cluster is a hard FAILED line — every fleet total is an
        # explicit lower bound until it resyncs, and the operator
        # running -doctor must see that verdict, not derive it.
        def _federation():
            from kubernetesclustercapacity_tpu.resilience import RetryPolicy
            from kubernetesclustercapacity_tpu.service.client import (
                CapacityClient,
            )

            with CapacityClient(
                *federation_addr,
                connect_timeout_s=5.0,
                timeout_s=5.0,
                retry=RetryPolicy(max_attempts=2, base_delay_s=0.1),
                deadline_s=5.0,
            ) as c:
                status = c.fed_status()
            if not status.get("enabled", False):
                return "not configured (no clusters attached)"
            counts = status.get("counts", {})
            parts = [
                f"{counts.get('total')} cluster(s)",
                f"fresh={counts.get('fresh')}",
                f"stale={counts.get('stale')}",
                f"lost={counts.get('lost')}",
            ]
            gens = [
                f"{name}@{c_.get('generation')}"
                for name, c_ in sorted(
                    status.get("clusters", {}).items()
                )
            ]
            if gens:
                parts.append("generations: " + " ".join(gens))
            excluded = status.get("excluded", [])
            if excluded:
                return (
                    "FAILED: cluster(s) lost — "
                    + ", ".join(excluded)
                    + " excluded from fleet totals; "
                    + " ".join(parts)
                )
            return "ok: " + " ".join(parts)

        check("federation", _federation)
    return checks


def healthy(checks: list[tuple[str, str]]) -> bool:
    """True when no check reports a hard failure (HUNG/FAILED/DISABLED).

    "unavailable"/"degraded" results are soft (the CLI still works on
    fallback paths) and do not fail the exit code.
    """
    return not any(
        result.startswith(("HUNG", "FAILED", "DISABLED"))
        for _, result in checks
    )


def run_doctor(
    *,
    backend_timeout_s: float = 30.0,
    probe_code: str | None = None,
    service_addr: tuple[str, int] | None = None,
    federation_addr: tuple[str, int] | None = None,
) -> tuple[str, int]:
    """Render the report; returns ``(text, exit_code)``.

    Exit code 1 when any check is a hard failure (HUNG/FAILED/DISABLED)
    so wrappers and CI gates can trust the command, not parse its prose.
    """
    t0 = time.time()
    checks = doctor_report(
        backend_timeout_s=backend_timeout_s,
        probe_code=probe_code,
        service_addr=service_addr,
        federation_addr=federation_addr,
    )
    width = max(len(name) for name, _ in checks)
    lines = [f"{name:<{width}}  {result}" for name, result in checks]
    lines.append(f"{'elapsed':<{width}}  {time.time() - t0:.1f}s")
    return "\n".join(lines), (0 if healthy(checks) else 1)
