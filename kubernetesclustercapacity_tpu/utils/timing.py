"""Timing / profiling harness (SURVEY.md §5 "tracing / profiling").

The reference's only observability is ad-hoc ``fmt.Printf`` progress lines;
it publishes no timings at all.  This module is the framework's built-in
instrumentation: phase-scoped wall-clock timers (snapshot → pack → kernel →
report), latency statistics for the BASELINE metrics (scenarios/sec, p50
sweep latency), and an optional ``jax.profiler`` trace hook for XLA-level
inspection.

Device-timing note: JAX dispatch is async — a phase that launches a kernel
returns before the kernel finishes.  :func:`timed` takes a ``block`` result
(anything acceptable to ``jax.block_until_ready``) so kernel phases measure
completion, not dispatch.
"""

from __future__ import annotations

import contextlib
import json
import time
from dataclasses import dataclass, field

import numpy as np

__all__ = ["PhaseTimer", "LatencyStats", "measure_latency", "trace"]


class _PhaseHandle:
    """Yielded by :meth:`PhaseTimer.phase`; lets the body register device
    results the phase must wait for (JAX dispatch is async)."""

    def __init__(self) -> None:
        self._blockers: list = []

    def block(self, result):
        """Register a result to ``jax.block_until_ready`` before the phase
        closes; returns it unchanged so it can be used inline."""
        self._blockers.append(result)
        return result


@dataclass
class PhaseTimer:
    """Accumulates named phase durations; renders a report or JSON.

    >>> t = PhaseTimer()
    >>> with t.phase("pack"):
    ...     snapshot = snapshot_from_fixture(fx)
    >>> with t.phase("kernel") as ph:
    ...     totals = ph.block(sweep(...))  # phase waits for the device
    >>> print(t.report())
    """

    phases: dict[str, float] = field(default_factory=dict)

    @contextlib.contextmanager
    def phase(self, name: str):
        handle = _PhaseHandle()
        t0 = time.perf_counter()
        try:
            yield handle
        finally:
            if handle._blockers:
                import jax

                jax.block_until_ready(handle._blockers)
            self.phases[name] = self.phases.get(name, 0.0) + (
                time.perf_counter() - t0
            )

    def report(self) -> str:
        total = sum(self.phases.values())
        lines = [f"{'PHASE':<24} {'SECONDS':>10} {'SHARE':>8}"]
        for name, secs in self.phases.items():
            share = (secs / total * 100) if total else 0.0
            lines.append(f"{name:<24} {secs:>10.4f} {share:>7.1f}%")
        lines.append(f"{'total':<24} {total:>10.4f}")
        return "\n".join(lines)

    def json(self) -> str:
        return json.dumps(
            {k: round(v, 6) for k, v in self.phases.items()}
        )


@dataclass(frozen=True)
class LatencyStats:
    """Latency distribution of repeated runs, in milliseconds.

    Rejects empty samples at construction: every accessor percentiles
    over ``samples_ms``, and ``np.percentile([])`` raises an opaque
    IndexError long after the real mistake (a zero-rep measurement).
    """

    samples_ms: tuple

    def __post_init__(self) -> None:
        if not self.samples_ms:
            raise ValueError(
                "LatencyStats needs at least one sample; an empty "
                "samples_ms usually means the measurement ran 0 reps"
            )

    @property
    def p50(self) -> float:
        return float(np.percentile(self.samples_ms, 50))

    @property
    def p10(self) -> float:
        return float(np.percentile(self.samples_ms, 10))

    @property
    def p90(self) -> float:
        return float(np.percentile(self.samples_ms, 90))

    def throughput(self, items_per_run: int) -> float:
        """items/sec at p50 — e.g. scenarios/sec for a sweep."""
        return items_per_run / (self.p50 / 1e3)

    def json(self) -> str:
        return json.dumps(
            {
                "p10_ms": round(self.p10, 3),
                "p50_ms": round(self.p50, 3),
                "p90_ms": round(self.p90, 3),
                "runs": len(self.samples_ms),
            }
        )


def measure_latency(fn, *, reps: int = 30, warmup: int = 1) -> LatencyStats:
    """Time ``fn()`` (which must block on its own result) ``reps`` times.

    ``reps`` must be >= 1 and ``warmup`` >= 0 — validated here, because
    ``reps=0`` would otherwise produce an empty sample set that only
    explodes later, inside a percentile deep in reporting code.
    """
    if reps < 1:
        raise ValueError(f"reps must be >= 1, got {reps}")
    if warmup < 0:
        raise ValueError(f"warmup must be >= 0, got {warmup}")
    for _ in range(warmup):
        fn()
    samples = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        samples.append((time.perf_counter() - t0) * 1e3)
    return LatencyStats(samples_ms=tuple(samples))


@contextlib.contextmanager
def trace(log_dir: str):
    """``jax.profiler`` trace scope — view with TensorBoard/XProf.

    Wrap a sweep to capture XLA execution timelines::

        with trace("/tmp/kcc-trace"):
            sweep_snapshot(snap, grid)
    """
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
