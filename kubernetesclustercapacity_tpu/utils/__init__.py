"""Utility layer: quantity codecs, timing/profiling, snapshot IO."""
