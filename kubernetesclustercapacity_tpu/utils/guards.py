"""Runtime value guards via ``jax.experimental.checkify`` (SURVEY.md §5).

The reference's failure story is panic/exit (its race-detection and
sanitizer rows are empty — single goroutine, nothing shared).  The JAX-side
analog of sanitizers is functional purity plus *checkified* kernels:
:func:`checked_fit_totals` runs the fit with in-graph assertions that
surface as Python errors instead of silently wrong totals — used in tests
and debugging sessions, never on the bench hot path (checkify adds ops).

Checks:

* nonzero requests (the reference integer-divide-by-zero panic sites,
  ``ClusterCapacity.go:123,129``);
* no negative snapshot values (wrapped uint64 bit patterns reaching a mode
  that assumes non-negativity);
* total within int64 headroom of the node count (sum cannot have wrapped).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import checkify

from kubernetesclustercapacity_tpu.ops.fit import (
    fit_per_node,
    fit_per_node_multi,
)

__all__ = ["checked_fit_totals", "checked_fit_totals_multi"]


def _checked_impl(
    alloc_cpu, alloc_mem, alloc_pods, used_cpu, used_mem, pods_count,
    healthy, cpu_req, mem_req,
):
    checkify.check(cpu_req != 0, "cpuRequests is zero: the reference panics "
                   "with integer divide by zero (ClusterCapacity.go:123)")
    checkify.check(mem_req != 0, "memRequests is zero: the reference panics "
                   "with integer divide by zero (ClusterCapacity.go:129)")
    checkify.check(
        jnp.all(alloc_cpu >= 0) & jnp.all(used_cpu >= 0),
        "negative CPU values in snapshot (wrapped uint64 bit pattern)",
    )
    checkify.check(
        jnp.all(alloc_mem >= 0) & jnp.all(used_mem >= 0),
        "negative memory values in snapshot (wrapped int64 sum)",
    )
    checkify.check(
        jnp.all(alloc_pods >= 0) & jnp.all(pods_count >= 0),
        "negative pod counts in snapshot",
    )
    fits = fit_per_node(
        alloc_cpu, alloc_mem, alloc_pods, used_cpu, used_mem, pods_count,
        healthy, cpu_req, mem_req, mode="reference",
    )
    total = jnp.sum(fits)
    n = fits.shape[0]
    # Each |fit| < 2^31 on sane inputs, so |total| < n * 2^31; anything
    # larger means the int64 sum wrapped.
    checkify.check(
        jnp.abs(total) <= jnp.int64(n) * jnp.int64(2**31),
        "total replica count out of range: int64 sum may have wrapped",
    )
    return total


_checked = jax.jit(checkify.checkify(_checked_impl))


def checked_fit_totals(
    alloc_cpu, alloc_mem, alloc_pods, used_cpu, used_mem, pods_count,
    healthy, cpu_req, mem_req,
) -> int:
    """Fit total with in-graph validity checks; raises on violation."""
    err, total = _checked(
        jnp.asarray(alloc_cpu, jnp.int64),
        jnp.asarray(alloc_mem, jnp.int64),
        jnp.asarray(alloc_pods, jnp.int64),
        jnp.asarray(used_cpu, jnp.int64),
        jnp.asarray(used_mem, jnp.int64),
        jnp.asarray(pods_count, jnp.int64),
        jnp.asarray(healthy, jnp.bool_),
        jnp.asarray(cpu_req, jnp.int64),
        jnp.asarray(mem_req, jnp.int64),
    )
    err.throw()
    return int(total)


def _checked_multi_impl(
    alloc_rn, used_rn, alloc_pods, pods_count, healthy, reqs_r
):
    checkify.check(
        jnp.all(reqs_r >= 0),
        "negative resource request in the R-dim grid (zero means "
        "does-not-consume; negative has no defined semantics)",
    )
    checkify.check(
        jnp.all(alloc_rn >= 0) & jnp.all(used_rn >= 0),
        "negative values in the [R, N] resource matrix",
    )
    checkify.check(
        jnp.all(alloc_pods >= 0) & jnp.all(pods_count >= 0),
        "negative pod counts in snapshot",
    )
    fits = fit_per_node_multi(
        alloc_rn, used_rn, alloc_pods, pods_count, healthy, reqs_r,
        mode="strict",
    )
    total = jnp.sum(fits)
    n = fits.shape[0]
    checkify.check(
        jnp.abs(total) <= jnp.int64(n) * jnp.int64(2**31),
        "total replica count out of range: int64 sum may have wrapped",
    )
    return total


_checked_multi = jax.jit(checkify.checkify(_checked_multi_impl))


def checked_fit_totals_multi(
    alloc_rn, used_rn, alloc_pods, pods_count, healthy, reqs_r
) -> int:
    """R-dim (strict) fit total with in-graph validity checks."""
    err, total = _checked_multi(
        jnp.asarray(alloc_rn, jnp.int64),
        jnp.asarray(used_rn, jnp.int64),
        jnp.asarray(alloc_pods, jnp.int64),
        jnp.asarray(pods_count, jnp.int64),
        jnp.asarray(healthy, jnp.bool_),
        jnp.asarray(reqs_r, jnp.int64),
    )
    err.throw()
    return int(total)
