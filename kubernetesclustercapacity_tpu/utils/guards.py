"""Runtime value guards via ``jax.experimental.checkify`` (SURVEY.md §5).

The reference's failure story is panic/exit (its race-detection and
sanitizer rows are empty — single goroutine, nothing shared).  The JAX-side
analog of sanitizers is functional purity plus *checkified* kernels:
:func:`checked_fit_totals` runs the fit with in-graph assertions that
surface as Python errors instead of silently wrong totals — used in tests
and debugging sessions, never on the bench hot path (checkify adds ops).

Checks:

* nonzero requests (the reference integer-divide-by-zero panic sites,
  ``ClusterCapacity.go:123,129``);
* no negative snapshot values (wrapped uint64 bit patterns reaching a mode
  that assumes non-negativity);
* the sum-of-fits wrap guard: accepted only when ``n * max|fit|`` proves
  the int64 total cannot have wrapped (a data-derived bound — huge but
  legitimate per-node fits are not false positives).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import checkify

from kubernetesclustercapacity_tpu.ops.fit import (
    fit_per_node,
    fit_per_node_multi,
)

__all__ = ["checked_fit_totals", "checked_fit_totals_multi"]


def _checked_impl(
    alloc_cpu, alloc_mem, alloc_pods, used_cpu, used_mem, pods_count,
    healthy, cpu_req, mem_req,
):
    checkify.check(cpu_req != 0, "cpuRequests is zero: the reference panics "
                   "with integer divide by zero (ClusterCapacity.go:123)")
    checkify.check(mem_req != 0, "memRequests is zero: the reference panics "
                   "with integer divide by zero (ClusterCapacity.go:129)")
    checkify.check(
        jnp.all(alloc_cpu >= 0) & jnp.all(used_cpu >= 0),
        "negative CPU values in snapshot (wrapped uint64 bit pattern)",
    )
    checkify.check(
        jnp.all(alloc_mem >= 0) & jnp.all(used_mem >= 0),
        "negative memory values in snapshot (wrapped int64 sum)",
    )
    checkify.check(
        jnp.all(alloc_pods >= 0) & jnp.all(pods_count >= 0),
        "negative pod counts in snapshot",
    )
    fits = fit_per_node(
        alloc_cpu, alloc_mem, alloc_pods, used_cpu, used_mem, pods_count,
        healthy, cpu_req, mem_req, mode="reference",
    )
    total = jnp.sum(fits)
    _check_sum_headroom(fits)
    return total


def _check_sum_headroom(fits):
    """Sum-of-fits wrap guard with a bound derived from the DATA.

    ``n * max|fit|`` bounds ``|sum|`` exactly; when that product (taken
    in float64) stays under 2^62, the true sum is under 2^62·(1+ε) —
    far inside int64 — so the computed total cannot have wrapped and is
    accepted.  (The 2^62-vs-2^63 slack IS the margin absorbing the
    float64 rounding of the product.)  Legitimately huge per-node fits
    (alloc_pods beyond 2^31 is representable and parses fine) therefore
    never trip a false positive; the guard flags only inputs whose
    a-priori bound genuinely reaches wrap range.
    """
    n = fits.shape[0]
    max_abs = jnp.max(jnp.abs(fits)) if n else jnp.int64(0)
    bound_f = jnp.float64(n) * max_abs.astype(jnp.float64)
    checkify.check(
        bound_f < jnp.float64(2.0**62),
        "total replica count unverifiable: n * max|fit| reaches int64 "
        "wrap range, the sum may have wrapped",
    )


_checked = jax.jit(checkify.checkify(_checked_impl))


def checked_fit_totals(
    alloc_cpu, alloc_mem, alloc_pods, used_cpu, used_mem, pods_count,
    healthy, cpu_req, mem_req,
) -> int:
    """Fit total with in-graph validity checks; raises on violation."""
    err, total = _checked(
        jnp.asarray(alloc_cpu, jnp.int64),
        jnp.asarray(alloc_mem, jnp.int64),
        jnp.asarray(alloc_pods, jnp.int64),
        jnp.asarray(used_cpu, jnp.int64),
        jnp.asarray(used_mem, jnp.int64),
        jnp.asarray(pods_count, jnp.int64),
        jnp.asarray(healthy, jnp.bool_),
        jnp.asarray(cpu_req, jnp.int64),
        jnp.asarray(mem_req, jnp.int64),
    )
    err.throw()
    return int(total)


def _checked_multi_impl(
    alloc_rn, used_rn, alloc_pods, pods_count, healthy, reqs_r
):
    checkify.check(
        jnp.all(reqs_r >= 0),
        "negative resource request in the R-dim grid (zero means "
        "does-not-consume; negative has no defined semantics)",
    )
    checkify.check(
        jnp.all(alloc_rn >= 0) & jnp.all(used_rn >= 0),
        "negative values in the [R, N] resource matrix",
    )
    checkify.check(
        jnp.all(alloc_pods >= 0) & jnp.all(pods_count >= 0),
        "negative pod counts in snapshot",
    )
    fits = fit_per_node_multi(
        alloc_rn, used_rn, alloc_pods, pods_count, healthy, reqs_r,
        mode="strict",
    )
    total = jnp.sum(fits)
    _check_sum_headroom(fits)
    return total


_checked_multi = jax.jit(checkify.checkify(_checked_multi_impl))


def checked_fit_totals_multi(
    alloc_rn, used_rn, alloc_pods, pods_count, healthy, reqs_r
) -> int:
    """R-dim (strict) fit total with in-graph validity checks."""
    err, total = _checked_multi(
        jnp.asarray(alloc_rn, jnp.int64),
        jnp.asarray(used_rn, jnp.int64),
        jnp.asarray(alloc_pods, jnp.int64),
        jnp.asarray(pods_count, jnp.int64),
        jnp.asarray(healthy, jnp.bool_),
        jnp.asarray(reqs_r, jnp.int64),
    )
    err.throw()
    return int(total)
