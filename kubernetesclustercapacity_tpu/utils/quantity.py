"""Quantity codecs (L3): CPU-millicore and byte-quantity parsing.

Two families of codecs live here:

* **Reference-exact codecs** reproduce the reference's parsing bit-for-bit,
  including its quirks, so bit-exact parity against the reference CPU path is
  possible (SURVEY.md §2.2):

  - :func:`cpu_to_milli_reference` — semantics of ``convertCPUToMilis``
    (reference ``src/KubeAPI/ClusterCapacity.go:301-319``): trailing ``m``
    stripped and value used as-is, otherwise integer × 1000; *any* parse
    failure yields 0 (not an error).
  - :func:`to_bytes_reference` — semantics of ``bytefmt.ToBytes`` (reference
    ``src/bytefmt/bytes.go:75-105``): ALL prefixes are base-2 (``MB == MiB ==
    1024·1024``), a plain number with no unit is an error, value ≤ 0 is an
    error, and ``GI``/``TI`` are rejected while ``MI``/``KI`` parse (the
    upstream suffix-table asymmetry).
  - :func:`byte_size` / :func:`to_megabytes` — the reference's formatting
    helpers (``bytes.go:32-68``; dead code there, kept for API parity).

* **Strict codecs** implement the real Kubernetes ``resource.Quantity``
  grammar (``<signedNumber><suffix>`` with binary ``Ki..Ei``, decimal SI
  ``n..E`` and scientific ``e``/``E`` exponents) with exact decimal
  arithmetic, matching ``Quantity.Value()`` / ``Quantity.MilliValue()``
  round-up semantics.  The reference itself uses this API for **pod memory**
  (``ClusterCapacity.go:285-286`` calls ``Resources...Memory().Value()``), so
  even bug-compatible mode needs the strict parser.

All functions are pure Python on scalars — parsing happens once at snapshot
ingestion, never inside the TPU hot loop.
"""

from __future__ import annotations

import functools
import unicodedata
from dataclasses import dataclass
from fractions import Fraction

# Quantity strings repeat massively across a cluster (every node of a
# machine type advertises the same "8" / "16Gi" / "110"; most pods share a
# handful of request shapes), so the pure string→value codecs are memoized.
# 10k-node ingestion is dominated by exact-Fraction parsing without this
# (SURVEY.md §7 "snapshot ingestion at 10k nodes").  Bounded so hostile
# streams of distinct strings cannot grow memory; failures raise and are
# deliberately NOT cached (lru_cache does not cache exceptions).
_PARSE_CACHE_SIZE = 1 << 16

__all__ = [
    "QuantityParseError",
    "go_atoi",
    "go_atoi_clamped",
    "int64_bits",
    "cpu_to_milli_reference",
    "cpu_parse_error_payload",
    "to_bytes_reference",
    "byte_size",
    "to_megabytes",
    "Quantity",
    "parse_quantity",
    "cpu_to_milli_strict",
    "mem_to_bytes_strict",
]

_UINT64_MOD = 1 << 64

# Base-2 multipliers of the reference byte codec (bytes.go:15-21).
_KIB = 1024
_MIB = 1024 * _KIB
_GIB = 1024 * _MIB
_TIB = 1024 * _GIB

_INVALID_BYTE_QUANTITY_MSG = (
    "byte quantity must be a positive integer with a unit of measurement like "
    "M, MB, MiB, G, GiB, or GB"
)


class QuantityParseError(ValueError):
    """Raised when a quantity string cannot be parsed."""


def go_atoi(s: str) -> int | None:
    """Base-10 integer parse with Go ``strconv.Atoi`` acceptance rules.

    Optional single leading ``+``/``-``, then one or more ASCII digits.  No
    whitespace, no underscores, no empty string, and — like Go — values
    outside int64 range are a range error.  Returns ``None`` on failure
    (callers decide the failure semantics).
    """
    if not s:
        return None
    body = s[1:] if s[0] in "+-" else s
    if not body or not body.isascii() or not body.isdigit():
        return None
    value = int(s, 10)
    if not (-(1 << 63) <= value < (1 << 63)):
        return None
    return value


def go_atoi_clamped(s: str) -> int:
    """The VALUE Go ``strconv.Atoi`` returns alongside a failed parse.

    Syntax errors return 0, but range errors return the int64-CLAMPED
    value (``strconv.ParseInt`` semantics) — and the reference's fatal
    replicas line prints that value (``fmt.Println(..., replicas, ...)``
    at ``ClusterCapacity.go:81``), so byte parity needs it.
    """
    body = s[1:] if s[:1] in "+-" else s
    if body and body.isascii() and body.isdigit():
        value = int(s, 10)
        if value >= 1 << 63:
            return (1 << 63) - 1
        if value < -(1 << 63):
            return -(1 << 63)
        return value
    return 0


def int64_bits(u: int) -> int:
    """Reinterpret an arbitrary integer as its int64 bit pattern
    (mod 2^64, two's complement) — the carrier the kernels/native code
    use for Go's uint64 values."""
    u %= 1 << 64
    return u - (1 << 64) if u >= 1 << 63 else u


@functools.lru_cache(maxsize=_PARSE_CACHE_SIZE)
def cpu_parse_error_payload(cpu: str) -> str | None:
    """The ``%s`` of the reference codec's error line, or ``None``.

    ``convertCPUToMilis`` prints ``"\\nError converting string to int for
    %s\\n"`` with the SUFFIX-STRIPPED string whenever ``Atoi`` fails
    (``ClusterCapacity.go:314-317``) — transcript parity replays these.
    """
    body = cpu[:-1] if cpu.endswith("m") else cpu
    return None if go_atoi(body) is not None else body


# Go ``unicode.IsSpace`` == the Unicode White_Space property — the exact
# set ``strings.TrimSpace`` trims (``bytes.go:76``).  Python's bare
# ``str.strip()`` trims a SUPERSET (U+001C–U+001F, the ASCII separator
# controls, are Python-space but not Go-space), so the reference codec
# trims with this explicit set to stay byte-compatible: ``"\x1c100MB"``
# must FAIL to parse, as it does in Go.
_GO_SPACE_CHARS = (
    "\t\n\v\f\r \x85\xa0\u1680"
    "\u2000\u2001\u2002\u2003\u2004\u2005\u2006\u2007\u2008"
    "\u2009\u200a\u2028\u2029\u202f\u205f\u3000"
)


_GO_QUOTE_ESCAPES = {
    "\a": "\\a", "\b": "\\b", "\f": "\\f", "\n": "\\n",
    "\r": "\\r", "\t": "\\t", "\v": "\\v",
    "\\": "\\\\", '"': '\\"',
}


def _go_is_print(ch: str) -> bool:
    """Go ``unicode.IsPrint``: letters, marks, numbers, punctuation,
    symbols, and the ASCII space — category classes L/M/N/P/S plus
    U+0020 (doc of ``unicode.IsPrint``; graphic minus the other spaces).
    """
    if ch == " ":
        return True
    return unicodedata.category(ch)[0] in "LMNPS"


def go_quote(s: str) -> str:
    """Go ``strconv.Quote`` — the ``%q`` verb's quoting, byte-exact.

    The reference's fatal replicas line embeds ``strconv.Atoi``'s error,
    whose ``parsing %q`` quotes the input: double-quote wrapping, the
    standard single-char escapes, ``\\xhh`` for other non-printable
    ASCII, ``\\uhhhh`` / ``\\Uhhhhhhhh`` for non-printable non-ASCII
    (``unicode.IsPrint`` decides).  Invalid UTF-8 bytes in argv arrive
    here as surrogate escapes (PEP 383) and print as ``\\xhh`` of the
    original byte, exactly as Go quotes invalid bytes.
    """
    out = ['"']
    for ch in s:
        if ch in _GO_QUOTE_ESCAPES:
            out.append(_GO_QUOTE_ESCAPES[ch])
        elif _go_is_print(ch):
            out.append(ch)
        else:
            cp = ord(ch)
            if 0xDC80 <= cp <= 0xDCFF:  # PEP 383 surrogate: a raw byte
                out.append(f"\\x{cp - 0xDC00:02x}")
            elif cp < 0x80:
                out.append(f"\\x{cp:02x}")
            elif cp < 0x10000:
                out.append(f"\\u{cp:04x}")
            else:
                out.append(f"\\U{cp:08x}")
    out.append('"')
    return "".join(out)


def go_atoi_error(s: str) -> str:
    """The ``strconv.Atoi`` error text Go prints for a failed parse.

    Byte-parity helper for the reference's fatal replicas line
    (``ClusterCapacity.go:81``): syntactically-valid digits that overflow
    int64 are a range error, anything else is a syntax error.  The input
    is quoted with full ``%q`` semantics (:func:`go_quote`), so control
    characters and non-printables in flag values match Go byte-for-byte.
    """
    body = s[1:] if s[:1] in "+-" else s
    if body and body.isascii() and body.isdigit():
        return f"strconv.Atoi: parsing {go_quote(s)}: value out of range"
    return f"strconv.Atoi: parsing {go_quote(s)}: invalid syntax"


@functools.lru_cache(maxsize=_PARSE_CACHE_SIZE)
def cpu_to_milli_reference(cpu: str) -> int:
    """CPU quantity string → millicores, reference semantics.

    Mirrors ``convertCPUToMilis`` (``ClusterCapacity.go:301-319``):

    * ``"250m"`` → 250 (trailing ``m`` stripped, value as-is)
    * ``"2"``    → 2000 (no suffix → cores × 1000)
    * any parse failure (``"0.5"``, ``"100Mi"``, ``""``, ``"1e2"``) → **0**
      — the reference prints an error and carries on with zero.
    * negative inputs wrap through Go's ``uint64(int(...))`` conversion —
      ``"-5"`` → 2**64 − 5000.  Reproduced so the codec is total on the same
      domain as the reference.
    """
    has_m = cpu.endswith("m")
    if has_m:
        cpu = cpu[:-1]
    value = go_atoi(cpu)
    if value is None:
        return 0
    if not has_m:
        value *= 1000
    return value % _UINT64_MOD


def _go_parse_float(s: str) -> float | None:
    """Approximation of Go ``strconv.ParseFloat(s, 64)`` for the codec's use.

    Accepts decimal and exponent forms (and underscore digit separators, as
    both languages do).  Whitespace is rejected (Python ``float()`` would
    strip it; Go does not), non-ASCII input is rejected (Go parses ASCII
    only; Python ``float()`` would transform Unicode decimal digits like
    ``"١٥"``), and overflow-to-infinity is a range error like Go's
    ``ErrRange``.  Divergence (documented): Go also accepts ``inf`` /
    ``nan`` / hex-float spellings, for which the reference's downstream
    ``int64(float * mult)`` conversion is unspecified — those spellings are
    rejected here instead of reproducing undefined behavior.
    """
    if s != s.strip() or not s.isascii():
        return None
    t = s.lower().lstrip("+-")
    if t.startswith(("inf", "nan")) or t.startswith("0x"):
        return None
    try:
        value = float(s)
    except ValueError:
        return None
    if value in (float("inf"), float("-inf")):
        return None
    return value


@functools.lru_cache(maxsize=_PARSE_CACHE_SIZE)
def to_bytes_reference(s: str) -> int:
    """Byte quantity string → bytes, reference ``bytefmt.ToBytes`` semantics.

    Mirrors ``bytes.go:75-105`` exactly:

    * input is whitespace-trimmed and uppercased, then split at the first
      letter; **no letter → error** (plain ``"1073741824"`` fails);
    * numeric part parsed as float; parse failure or value ≤ 0 → error;
    * suffix table (ALL base-2): ``T|TB|TIB``, ``G|GB|GIB``, ``M|MB|MIB|MI``,
      ``K|KB|KIB|KI``, ``B``; anything else → error.  Note ``MI``/``KI`` are
      accepted but ``GI``/``TI`` are **not** — so a node advertising
      ``"16Gi"`` fails to parse (and the reference then zeroes that node's
      memory, ``ClusterCapacity.go:202-206``);
    * result truncates toward zero: ``int64(value * multiplier)``.

    Raises :class:`QuantityParseError` with the reference's error message.
    """
    # Go's TrimSpace set exactly — not Python's broader str.strip() set.
    s = s.strip(_GO_SPACE_CHARS).upper()

    letter_idx = -1
    for i, ch in enumerate(s):
        if ch.isalpha():
            letter_idx = i
            break
    if letter_idx == -1:
        raise QuantityParseError(_INVALID_BYTE_QUANTITY_MSG)

    num_part, suffix = s[:letter_idx], s[letter_idx:]
    value = _go_parse_float(num_part)
    if value is None or value <= 0:
        raise QuantityParseError(_INVALID_BYTE_QUANTITY_MSG)

    if suffix in ("T", "TB", "TIB"):
        mult = _TIB
    elif suffix in ("G", "GB", "GIB"):
        mult = _GIB
    elif suffix in ("M", "MB", "MIB", "MI"):
        mult = _MIB
    elif suffix in ("K", "KB", "KIB", "KI"):
        mult = _KIB
    elif suffix == "B":
        mult = 1
    else:
        raise QuantityParseError(_INVALID_BYTE_QUANTITY_MSG)

    result = int(value * mult)
    # Go's int64(float64) conversion is unspecified when out of range; on
    # amd64/arm64 it produces math.MinInt64, which is what a node advertising
    # absurd memory would get in the reference.
    if not (-(1 << 63) <= result < (1 << 63)):
        result = -(1 << 63)
    return result


def byte_size(n_bytes: int) -> str:
    """Human-readable byte string, reference ``bytefmt.ByteSize`` semantics.

    Mirrors ``bytes.go:32-58``: largest base-2 unit with value ≥ 1, one
    decimal place with a trailing ``.0`` stripped; ``0`` → ``"0"``.  (Dead
    code in the reference — kept for component-inventory parity, SURVEY §2.1
    C7.)
    """
    value = float(n_bytes)
    if n_bytes >= _TIB:
        unit, value = "T", value / _TIB
    elif n_bytes >= _GIB:
        unit, value = "G", value / _GIB
    elif n_bytes >= _MIB:
        unit, value = "M", value / _MIB
    elif n_bytes >= _KIB:
        unit, value = "K", value / _KIB
    elif n_bytes >= 1:
        unit = "B"
    elif n_bytes == 0:
        return "0"
    else:
        unit = ""
    result = f"{value:.1f}"
    result = result.removesuffix(".0")
    return result + unit


def to_megabytes(s: str) -> int:
    """Parse a byte string and floor-divide to (base-2) megabytes (``bytes.go:61-68``)."""
    return to_bytes_reference(s) // _MIB


# ---------------------------------------------------------------------------
# Strict Kubernetes resource.Quantity grammar
# ---------------------------------------------------------------------------

_BINARY_SUFFIXES = {
    "Ki": 1024,
    "Mi": 1024**2,
    "Gi": 1024**3,
    "Ti": 1024**4,
    "Pi": 1024**5,
    "Ei": 1024**6,
}

_DECIMAL_SUFFIXES = {
    "n": Fraction(1, 10**9),
    "u": Fraction(1, 10**6),
    "m": Fraction(1, 10**3),
    "": Fraction(1),
    "k": Fraction(10**3),
    "M": Fraction(10**6),
    "G": Fraction(10**9),
    "T": Fraction(10**12),
    "P": Fraction(10**15),
    "E": Fraction(10**18),
}


@dataclass(frozen=True)
class Quantity:
    """Exact decimal quantity with Kubernetes round-up integer views.

    ``amount`` is the exact rational value (no float drift).  ``value()`` and
    ``milli_value()`` round **toward +∞** like Kubernetes ``Quantity.Value()``
    / ``MilliValue()`` (e.g. ``"100m".Value() == 1``, ``"1.5Gi".Value() ==
    1610612736``).
    """

    amount: Fraction
    original: str

    def value(self) -> int:
        return _int64_capped(_round_away_from_zero(self.amount))

    def milli_value(self) -> int:
        return _int64_capped(_round_away_from_zero(self.amount * 1000))

    def __float__(self) -> float:
        return float(self.amount)


def _round_away_from_zero(f: Fraction) -> int:
    """Upstream ``Quantity.Value()`` rounding: AWAY from zero, not toward
    +inf — ``"-100m".Value() == -1`` (ceil would give 0)."""
    if f.numerator >= 0:
        return -((-f.numerator) // f.denominator)
    return f.numerator // f.denominator


def _int64_capped(v: int) -> int:
    """Upstream caps values the int64 cannot hold (quantity.go: numbers
    larger than the format represents are capped at MaxInt64) instead of
    erroring — a 16E node serves max capacity, never a crash."""
    if v > _INT64_MAX_Q:
        return _INT64_MAX_Q
    if v < _INT64_MIN_Q:
        return _INT64_MIN_Q
    return v


_INT64_MAX_Q = (1 << 63) - 1
_INT64_MIN_Q = -(1 << 63)


@functools.lru_cache(maxsize=_PARSE_CACHE_SIZE)
def parse_quantity(s: str) -> Quantity:
    """Parse a Kubernetes ``resource.Quantity`` string exactly.

    Grammar: ``<signedNumber><suffix>`` where suffix is a binary SI unit
    (``Ki``..``Ei``, base-2), a decimal SI unit (``n u m k M G T P E`` or
    empty, base-10 — note lowercase ``k``, uppercase ``K`` is invalid), or a
    scientific exponent (``e``/``E`` with optional sign).  Arithmetic is exact
    (:class:`fractions.Fraction`), so ``"0.1"`` is one-tenth, not a float.

    This is the grammar behind ``Quantity.Value()`` that the reference relies
    on for pod memory (``ClusterCapacity.go:285-286``) and allocatable pods
    (``:208``).
    """
    original = s
    # No whitespace trim: upstream ParseQuantity rejects ' 1Gi' outright
    # (first byte must be a sign or digit).
    if not s:
        raise QuantityParseError("empty quantity string")

    sign = 1
    if s[0] in "+-":
        if s[0] == "-":
            sign = -1
        s = s[1:]

    i = 0
    while i < len(s) and (s[i].isdigit() or s[i] == "."):
        i += 1
    num_part, suffix = s[:i], s[i:]
    if not num_part or num_part == "." or num_part.count(".") > 1:
        raise QuantityParseError(f"invalid quantity number: {original!r}")
    if not num_part.replace(".", "").isascii():
        raise QuantityParseError(f"invalid quantity number: {original!r}")

    base = Fraction(num_part)

    if suffix in _BINARY_SUFFIXES:
        mult = Fraction(_BINARY_SUFFIXES[suffix])
    elif suffix in _DECIMAL_SUFFIXES:
        mult = _DECIMAL_SUFFIXES[suffix]
    elif suffix and suffix[0] in "eE":
        exp_str = suffix[1:]
        exp_body = exp_str[1:] if exp_str[:1] in "+-" else exp_str
        if not exp_body.isdigit() or not exp_body.isascii():
            # isascii: int()/isdigit would accept Unicode decimal digits
            # upstream's ASCII scanner rejects.
            raise QuantityParseError(f"invalid quantity exponent: {original!r}")
        exp = int(exp_str)
        # Real quantities span n (1e-9) to E (1e18), but the exponent must
        # not materialize 10**exp for hostile magnitudes.  Clamping is
        # only sound once the MANTISSA's own decimal magnitude is
        # accounted for (len(num_part) bounds it in both directions): with
        # |exp| <= 64 + len the value computes exactly in input-linear
        # space; beyond that bound the true value is provably > int64 max
        # (caps) or < 1 (rounds away from zero to +-1) — the clamped
        # multiplier lands in the same regime, so value()/milli_value()
        # return exactly what upstream's uncapped arithmetic would.
        bound = 64 + len(num_part)
        mult = Fraction(10) ** max(min(exp, bound), -bound)
    else:
        raise QuantityParseError(f"invalid quantity suffix: {original!r}")

    return Quantity(amount=sign * base * mult, original=original)


def cpu_to_milli_strict(s: str) -> int:
    """CPU quantity → millicores with full Kubernetes grammar (``"0.5"`` → 500)."""
    return parse_quantity(s).milli_value()


def mem_to_bytes_strict(s: str) -> int:
    """Memory quantity → bytes with full Kubernetes grammar (``"16Gi"`` parses)."""
    return parse_quantity(s).value()
