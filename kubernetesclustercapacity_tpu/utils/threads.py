"""Supervised thread targets: no worker dies silently.

``kccap-lint``'s ``hygiene-thread-death`` rule flags any
``threading.Thread`` target whose body can raise outside a
``try``/``except`` — a daemon worker killed by an unexpected exception
looks exactly like a quiet one, and every invariant it maintained
(heartbeats, queue drains, accept loops) stops holding with no signal.
:func:`supervised` is the standard fix: it wraps the target so an
escaping exception is counted, printed with its traceback to stderr,
and optionally handed to an ``on_death`` hook, instead of vanishing
into ``threading.excepthook``.

The worker's *expected* errors stay where they are (each loop's narrow
``except OSError`` etc. is the real protocol); supervision only
backstops the unexpected — the bug class that turns a race detector's
"no events from thread X" into a false all-clear.
"""

from __future__ import annotations

import sys
import threading
import traceback

__all__ = ["supervised", "death_count", "last_death"]

_lock = threading.Lock()
_deaths: list[tuple[str, str]] = []  # (thread name, "Type: msg")


def _record_death(name: str, exc: BaseException) -> None:
    desc = f"{type(exc).__name__}: {exc}"
    with _lock:
        _deaths.append((name, desc))
    print(
        f"kccap: supervised thread {name!r} died: {desc}",
        file=sys.stderr,
    )
    traceback.print_exc(file=sys.stderr)
    try:
        from kubernetesclustercapacity_tpu.telemetry.metrics import (
            REGISTRY,
            enabled,
        )

        if enabled():
            REGISTRY.counter(
                "kccap_thread_deaths_total",
                "Supervised worker threads killed by an unexpected "
                "exception, by thread name.",
                ("thread",),
            ).labels(thread=name).inc()
    except Exception:  # noqa: BLE001 - accounting must not re-raise
        pass


def supervised(target, *, name: str, on_death=None):
    """Wrap ``target`` so an escaping exception is loud, not silent.

    Returns a callable with the same signature; pass it as a
    ``threading.Thread`` target (positional ``args`` ride through).
    ``on_death(exc)`` runs after recording — the place to restore an
    invariant the dead worker owned (itself guarded: a raising hook is
    swallowed, the death is already on record).
    """

    def _supervised_runner(*args, **kwargs):
        try:
            target(*args, **kwargs)
        except Exception as e:  # noqa: BLE001 - the whole point
            _record_death(name, e)
            if on_death is not None:
                try:
                    on_death(e)
                except Exception:  # noqa: BLE001 - hook must not mask
                    pass

    _supervised_runner.__name__ = f"supervised[{name}]"
    return _supervised_runner


def death_count() -> int:
    """Supervised-thread deaths recorded so far in this process."""
    with _lock:
        return len(_deaths)


def last_death() -> tuple[str, str] | None:
    """The most recent ``(thread name, error)`` pair, or ``None``."""
    with _lock:
        return _deaths[-1] if _deaths else None
