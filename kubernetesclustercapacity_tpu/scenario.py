"""Scenario types: the what-if pod specs the capacity kernel evaluates.

The reference evaluates exactly ONE scenario per process run — the six CLI
flags at ``ClusterCapacity.go:50-62`` parsed at ``:64-83``.  Here a scenario
is a first-class value, and a :class:`ScenarioGrid` batches thousands of them
into dense arrays for the vectorized TPU kernel (the "scenario axis" of
SURVEY.md §2.3).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from kubernetesclustercapacity_tpu.utils.quantity import (
    QuantityParseError,
    cpu_parse_error_payload,
    cpu_to_milli_reference,
    go_atoi,
    go_atoi_clamped,
    go_atoi_error,
    int64_bits,
    to_bytes_reference,
)

__all__ = [
    "Scenario",
    "ScenarioGrid",
    "MultiResourceGrid",
    "ScenarioError",
    "scenario_from_flags",
    "random_scenario_grid",
]

# Reference CLI defaults (ClusterCapacity.go:57-61).
DEFAULT_CPU_REQUESTS = "100m"
DEFAULT_CPU_LIMITS = "200m"
DEFAULT_MEM_REQUESTS = "100mb"
DEFAULT_MEM_LIMITS = "200mb"
DEFAULT_REPLICAS = "1"


class ScenarioError(ValueError):
    """Invalid scenario flags — the analog of the reference's ``os.Exit(1)``.

    ``reference_line``, when set, is the BYTE-EXACT fatal line the reference
    would have printed before exiting (``ClusterCapacity.go:69,75,81``); the
    CLI prints it verbatim for error-path transcript parity.
    """

    def __init__(self, msg: str, *, reference_line: str | None = None):
        super().__init__(msg)
        self.reference_line = reference_line


@dataclass(frozen=True)
class Scenario:
    """One what-if pod spec: resource requests/limits + desired replicas.

    Units are the kernel's native integers: millicores and bytes.  Limits are
    carried for reporting parity only — like the reference, they never gate
    capacity (``ClusterCapacity.go:109-117``, SURVEY.md §2.4 Q2).
    """

    cpu_request_milli: int
    mem_request_bytes: int
    replicas: int
    cpu_limit_milli: int = 0
    mem_limit_bytes: int = 0
    # Transcript provenance: the suffix-stripped payloads of CPU flag
    # values the reference codec failed to parse (requests first, then
    # limits — main's conversion order at ClusterCapacity.go:64-65); the
    # reference prints one error line per payload before the parsed-input
    # line, and report.reference_report replays them.
    input_cpu_error_payloads: tuple[str, ...] = ()

    def validate(self) -> None:
        """Reject requests the reference would crash on.

        ``cpuRequests=0`` (or an unparseable value that the reference codec
        zeroed) causes an integer divide-by-zero panic at
        ``ClusterCapacity.go:123`` in the reference; ``memRequests`` can reach
        zero too — ``bytefmt`` checks positivity on the pre-multiplication
        float, so ``"0.5B"`` passes the check and truncates to 0 bytes,
        panicking at ``:129``.  Divergence (SURVEY.md §2.4 Q8): we validate
        instead of panicking.

        CPU requests are uint64 (the codec wraps negatives mod 2^64, e.g.
        ``-cpuRequests=-5`` → 2^64−5000): any NONZERO value is a valid —
        if enormous — divisor the reference runs with (every node fits 0),
        so only zero is rejected.  Negative replicas are likewise accepted:
        Go's ``Atoi`` parses them and the verdict comparison
        ``total >= replicas`` simply always schedules.
        """
        if self.cpu_request_milli % (1 << 64) == 0:
            raise ScenarioError(
                "cpuRequests must be nonzero (the reference integer-divides "
                "by it and would panic on zero)"
            )
        if self.mem_request_bytes <= 0:
            raise ScenarioError("memRequests must be > 0")


def scenario_from_flags(
    cpuRequests: str = DEFAULT_CPU_REQUESTS,
    cpuLimits: str = DEFAULT_CPU_LIMITS,
    memRequests: str = DEFAULT_MEM_REQUESTS,
    memLimits: str = DEFAULT_MEM_LIMITS,
    replicas: str = DEFAULT_REPLICAS,
) -> Scenario:
    """Parse flag strings exactly as the reference ``main`` does (``:64-83``).

    * CPU flags go through the reference codec — parse failure silently
      yields 0 there (it would then panic at division time; we defer to
      :meth:`Scenario.validate`).
    * Memory flags: a ``bytefmt`` parse error is fatal (``os.Exit(1)`` at
      ``:68-77``) → :class:`ScenarioError` here.
    * Replicas: Go ``strconv.Atoi`` failure is fatal (``:79-83``).
    """
    cpu_req = cpu_to_milli_reference(cpuRequests)
    cpu_lim = cpu_to_milli_reference(cpuLimits)
    # Requests convert before limits in main (:64-65); each failure is one
    # codec error line printed before the parsed-input line.
    cpu_error_payloads = tuple(
        p
        for p in (
            cpu_parse_error_payload(cpuRequests),
            cpu_parse_error_payload(cpuLimits),
        )
        if p is not None
    )
    # Fatal-flag errors carry the reference's exact Println output: the
    # zeroed value ToBytes/Atoi returned alongside its error, space-joined
    # (ClusterCapacity.go:69,75,81).
    try:
        mem_req = to_bytes_reference(memRequests)
    except QuantityParseError as e:
        raise ScenarioError(
            f"Invalid input memRequests: {e}",
            reference_line=f"ERROR : Invalid input memRequests = 0 {e} ...exiting",
        ) from e
    try:
        mem_lim = to_bytes_reference(memLimits)
    except QuantityParseError as e:
        raise ScenarioError(
            f"Invalid input memLimits: {e}",
            reference_line=f"ERROR : Invalid input memLimits = 0 {e} ...exiting",
        ) from e
    n_replicas = go_atoi(replicas)  # Go strconv.Atoi acceptance rules (:79)
    if n_replicas is None:
        # Go prints the VALUE Atoi returned with its error — 0 for syntax
        # errors but the int64-CLAMPED value for range errors (:81).
        raise ScenarioError(
            f"Invalid input replicas: {replicas!r}",
            reference_line=(
                f"ERROR : Invalid input replicas = "
                f"{go_atoi_clamped(replicas)} "
                f"{go_atoi_error(replicas)} ...exiting"
            ),
        )
    return Scenario(
        cpu_request_milli=cpu_req,
        mem_request_bytes=mem_req,
        replicas=n_replicas,
        cpu_limit_milli=cpu_lim,
        mem_limit_bytes=mem_lim,
        input_cpu_error_payloads=cpu_error_payloads,
    )


@dataclass(frozen=True)
class ScenarioGrid:
    """A batch of S scenarios as dense arrays — the kernel's scenario axis.

    ``cpu_request_milli`` and ``mem_request_bytes`` are int64 ``[S]`` arrays;
    ``replicas`` is int64 ``[S]``.  This is what ``vmap``/``pjit`` map over.
    """

    cpu_request_milli: np.ndarray
    mem_request_bytes: np.ndarray
    replicas: np.ndarray

    def __post_init__(self) -> None:
        for name in ("cpu_request_milli", "mem_request_bytes", "replicas"):
            arr = np.asarray(getattr(self, name), dtype=np.int64)
            object.__setattr__(self, name, arr)
        if not (
            self.cpu_request_milli.shape
            == self.mem_request_bytes.shape
            == self.replicas.shape
        ) or self.cpu_request_milli.ndim != 1:
            raise ScenarioError("scenario arrays must be equal-length 1-D")

    @property
    def size(self) -> int:
        return int(self.cpu_request_milli.shape[0])

    def validate(self) -> None:
        # CPU entries are uint64 bit patterns in an int64 carrier (negative
        # = wrapped huge request, fits 0 everywhere, reference semantics) —
        # only a true zero is the divide-by-zero panic case (Q8).
        if (self.cpu_request_milli == 0).any():
            raise ScenarioError("all cpu requests must be nonzero")
        if (self.mem_request_bytes <= 0).any():
            raise ScenarioError("all mem requests must be > 0")

    @classmethod
    def from_scenarios(cls, scenarios: list[Scenario]) -> "ScenarioGrid":
        return cls(
            # Scenario carries raw uint64 CPU values (printing parity);
            # the arrays carry their int64 bit patterns (kernel carrier).
            cpu_request_milli=np.array(
                [int64_bits(s.cpu_request_milli) for s in scenarios],
                dtype=np.int64,
            ),
            mem_request_bytes=np.array(
                [s.mem_request_bytes for s in scenarios], dtype=np.int64
            ),
            replicas=np.array([s.replicas for s in scenarios], dtype=np.int64),
        )

    def __getitem__(self, i: int) -> Scenario:
        return Scenario(
            cpu_request_milli=int(self.cpu_request_milli[i]),
            mem_request_bytes=int(self.mem_request_bytes[i]),
            replicas=int(self.replicas[i]),
        )


@dataclass(frozen=True)
class MultiResourceGrid:
    """An R-resource what-if grid (BASELINE config 4's scenario axis).

    ``resources`` names the request rows in order (``"cpu"`` in millicores,
    ``"memory"`` in bytes, anything else an extended-resource column of the
    snapshot, in its native unit); ``requests`` is ``[S, R]`` int64;
    ``replicas`` is ``[S]``.  The reference can express only the 2-resource
    case one scenario at a time (``ClusterCapacity.go:57-61``); this is the
    generalized axis the R-dim kernels sweep.
    """

    resources: tuple[str, ...]
    requests: np.ndarray
    replicas: np.ndarray

    def __post_init__(self) -> None:
        object.__setattr__(self, "resources", tuple(self.resources))
        if len(set(self.resources)) != len(self.resources):
            # Duplicates would silently alias the same snapshot column
            # twice (resource_matrix maps by name) — a typo'd grid must
            # fail loudly, not sweep min-over-duplicate-rows.
            raise ScenarioError(
                f"duplicate resource names in {self.resources!r}"
            )
        req = np.asarray(self.requests, dtype=np.int64)
        rep = np.asarray(self.replicas, dtype=np.int64)
        if req.ndim != 2 or req.shape[1] != len(self.resources):
            raise ScenarioError(
                f"requests must be [S, {len(self.resources)}], got {req.shape}"
            )
        if rep.shape != (req.shape[0],):
            raise ScenarioError("replicas must be [S]")
        object.__setattr__(self, "requests", req)
        object.__setattr__(self, "replicas", rep)

    @property
    def size(self) -> int:
        return int(self.requests.shape[0])

    @classmethod
    def from_grid(
        cls, grid: "ScenarioGrid", extended: dict | None = None
    ) -> "MultiResourceGrid":
        """Lift a 2-resource grid, optionally adding extended columns
        (``{resource_name: [S] per-replica requests}``)."""
        extended = dict(extended or {})
        names = ("cpu", "memory", *sorted(extended))
        cols = [grid.cpu_request_milli, grid.mem_request_bytes]
        for r in names[2:]:
            col = np.asarray(extended[r], dtype=np.int64)
            if col.shape != (grid.size,):
                raise ScenarioError(f"extended column {r!r} must be [S]")
            cols.append(col)
        return cls(
            resources=names,
            requests=np.stack(cols, axis=1),
            replicas=grid.replicas,
        )

    def validate(self) -> None:
        """cpu/memory must be positive (the reference's zero-request panic,
        SURVEY §2.4 Q8); extended requests may be 0 = "does not consume";
        negative anything is rejected."""
        if (self.requests < 0).any():
            raise ScenarioError("requests must be >= 0")
        for i, r in enumerate(self.resources):
            if r in ("cpu", "memory") and (self.requests[:, i] == 0).any():
                raise ScenarioError(f"all {r} requests must be > 0")
        if (self.replicas < 0).any():
            raise ScenarioError("all replicas must be >= 0")


def random_scenario_grid(
    n_scenarios: int,
    *,
    seed: int = 0,
    cpu_milli_range: tuple[int, int] = (50, 4000),
    mem_mib_range: tuple[int, int] = (64, 8192),
    replicas_range: tuple[int, int] = (1, 500),
) -> ScenarioGrid:
    """Random what-if grid (BASELINE config 3: "1k random (cpu,mem) grid").

    Memory requests are drawn in whole MiB so the fast int32 KiB-rescaled
    kernel path stays eligible; the exact path accepts arbitrary bytes.
    """
    rng = np.random.default_rng(seed)
    return ScenarioGrid(
        cpu_request_milli=rng.integers(
            cpu_milli_range[0], cpu_milli_range[1], size=n_scenarios, dtype=np.int64
        ),
        mem_request_bytes=rng.integers(
            mem_mib_range[0], mem_mib_range[1], size=n_scenarios, dtype=np.int64
        )
        * (1024 * 1024),
        replicas=rng.integers(
            replicas_range[0], replicas_range[1], size=n_scenarios, dtype=np.int64
        ),
    )
