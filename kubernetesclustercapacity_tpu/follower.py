"""Live cluster follower — the full list+watch informer loop.

The reference re-walks the whole apiserver (``1 + 2N + ΣP`` requests,
SURVEY.md §3.4) every time it runs.  This module is the end state of the
TPU-native redesign's ingestion side: list once (two paginated Lists,
:mod:`.kubeapi`), pack once (:class:`~.store.ClusterStore`), then stay
synced through the Kubernetes *watch* protocol — each cluster change costs
one streamed event and one per-row array update, and every
:meth:`ClusterFollower.snapshot` call is a consistent packed snapshot ready
for the fit kernels.

Watch-protocol handling follows the standard informer contract:

* resume each re-watch from the last seen ``metadata.resourceVersion``;
* ``BOOKMARK`` events only advance the resume version;
* ``ERROR`` events (e.g. 410 Gone — version expired) and any transport
  failure trigger a full relist+repack;
* ``ADDED``/``MODIFIED`` are applied as upserts (a relist race can replay
  either for an object the store already has), ``DELETED`` of an unknown
  object is ignored.
"""

from __future__ import annotations

import collections
import random
import threading
import time

from kubernetesclustercapacity_tpu.resilience import decorrelated_jitter

from kubernetesclustercapacity_tpu.kubeapi import (
    PDB_PATH,
    KubeAPIError,
    KubeClient,
    KubeConfig,
    KubeConfigError,
    node_to_fixture,
    pdb_to_fixture,
    pod_to_fixture,
)
from kubernetesclustercapacity_tpu.snapshot import ClusterSnapshot
from kubernetesclustercapacity_tpu.store import ClusterStore, StoreError

__all__ = ["ClusterFollower"]

_RESOURCES = {
    "/api/v1/nodes": ("Node", node_to_fixture),
    "/api/v1/pods": ("Pod", pod_to_fixture),
    # PDBs feed drain's eviction gate.  Optional: a 403/404 on the policy
    # API at relist marks them unavailable and their watch thread exits
    # (the other streams are unaffected); RBAC granted mid-run takes
    # effect at the next relist, streaming again after a restart.
    PDB_PATH: ("PodDisruptionBudget", pdb_to_fixture),
}

_FIXTURE_KEYS = {"Node": "nodes", "Pod": "pods", "PodDisruptionBudget": "pdbs"}

# Ceiling on the jittered failure backoff (client-go reflector's cap).
_BACKOFF_CAP_S = 30.0


class ClusterFollower:
    """Keep a packed :class:`ClusterStore` synced to a live cluster."""

    def __init__(
        self,
        kubeconfig: str | None = None,
        *,
        semantics: str = "reference",
        extended_resources: tuple[str, ...] = (),
        context: str | None = None,
        client_factory=None,
        on_event=None,
        stop_on_idle_window: bool = False,
        idle_rewatch_backoff: float = 1.0,
        resync_failure_deadline: float = 900.0,
        backoff_seed: int | None = None,
        registry=None,
        clock=time.monotonic,
    ) -> None:
        """``client_factory() -> KubeClient`` builds one client per stream
        (each watch occupies a connection); defaults to clients over the
        given kubeconfig.  ``on_event(kind, type, obj)`` is an optional
        observer called after each applied event — and with
        ``("*", "RELIST", {})`` after every error-path relist swaps in a
        fresh store, so consumers republish state that arrived without
        per-object events.

        A real apiserver regularly ends watch windows with no events and no
        version progress; the follower re-watches after
        ``idle_rewatch_backoff`` seconds (also the BASE of the exponential
        failure backoff, capped at 30 s).  Failure backoff uses
        decorrelated jitter (:mod:`..resilience`) so a fleet of followers
        recovering from a shared apiserver outage spreads its relists out
        instead of stampeding in lockstep; ``backoff_seed`` pins the
        jitter RNG for deterministic tests.  ``stop_on_idle_window=True``
        instead ends that resource's watch loop — ONLY for tests driving
        finite mock streams; in production it would silently stop syncing.

        ``resync_failure_deadline``: when BOTH the watch and the relist
        keep failing for this many seconds straight (expired unrefreshable
        credentials, revoked RBAC, dead apiserver), the follower goes
        fatal and stops — the served snapshot is visibly stale at that
        point, and the module contract is that staleness is never silent.

        ``registry`` is the :class:`~.telemetry.MetricsRegistry` holding
        this follower's sync counters — the single source of truth
        :meth:`stats` is a view over.  Default: a fresh private registry
        (per-follower counts, as before); the serve path passes the
        process registry so the scrape includes them.

        ``clock`` (monotonic seconds, injectable for deterministic
        staleness tests) feeds :meth:`last_relist_age_s` and
        :meth:`last_verified_age_s` — consumers computing freshness
        bounds read the follower's clock, never a second wall-clock of
        their own.
        """
        from kubernetesclustercapacity_tpu.telemetry.metrics import (
            MetricsRegistry,
        )
        if client_factory is None:
            # Validate the kubeconfig up front (fail fast on a bad file)...
            KubeConfig.load(kubeconfig, context=context)

            def client_factory() -> KubeClient:  # noqa: F811 - default
                # ...but re-resolve credentials per client: exec-plugin /
                # OIDC / tokenFile tokens expire (EKS: ~15 min), and a
                # factory pinned to the startup token would 401 on every
                # reconnect forever after expiry.
                return KubeClient(KubeConfig.load(kubeconfig, context=context))

        self._factory = client_factory
        self._resync_deadline = resync_failure_deadline
        self._semantics = semantics
        self._extended = tuple(extended_resources)
        self.on_event = on_event
        self._stop_on_idle_window = stop_on_idle_window
        self._idle_backoff = idle_rewatch_backoff
        self._lock = threading.Lock()
        self._store: ClusterStore | None = None
        self._synced = threading.Event()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # _versions, _epoch and _store share _lock: every read or write of
        # any of them happens under it (two watch threads + callers race).
        self._versions: dict[str, str] = {}
        self._epoch = 0  # bumped by every relist; stale streams stop applying
        self._clock = clock
        self._last_relist_t: float | None = None  # monotonic; /healthz age
        # Last instant the store was verifiably synced to the apiserver:
        # a completed relist OR an applied watch event (both prove the
        # stream was live then).  Guarded by _lock like the relist stamp.
        self._last_verified_t: float | None = None
        self._fatal: str | None = None
        self._pdb_unavailable = False  # policy API 403/404 at relist
        self._errors: collections.deque = collections.deque(maxlen=100)
        # Jittered-backoff RNG (seedable) + resilience counters, all
        # guarded by _lock.  _backoff_s tracks each stream's CURRENT
        # retry delay (0 = healthy) so info/doctor can see a struggling
        # sync loop, not just its final failure.
        self._backoff_rng = random.Random(backoff_seed)
        self._backoff_s: dict[str, float] = {}
        # The sync counters live in the registry (stats() and the
        # Prometheus scrape read the same cells); counter names keep the
        # stats()-dict keys as their last path segment so the two views
        # are visibly the same quantity.
        self.registry = registry if registry is not None else MetricsRegistry()
        self._counters = {
            name: self.registry.counter(
                f"kccap_follower_{name}_total", help_
            )
            for name, help_ in (
                ("relists", "Full list+repack cycles completed."),
                ("relist_failures", "Relist attempts that failed."),
                ("watch_failures", "Watch streams that failed/expired."),
                ("events_applied", "Watch events applied to the store."),
            )
        }
        self._m_backoff = self.registry.gauge(
            "kccap_follower_backoff_seconds",
            "Current retry backoff per watch stream (0 = healthy).",
            ("stream",),
        )
        # Live clients (watch streams mid-read, in-flight relists), guarded
        # by _lock: stop() severs their sockets so a reader parked in
        # readline() unblocks now, not after the watch watchdog.
        self._active_clients: set[KubeClient] = set()

    # -- lifecycle ---------------------------------------------------------
    def start(self, *, watch: bool = True) -> "ClusterFollower":
        """List+pack, then follow both watch streams in daemon threads.

        ``watch=False`` stops after the initial list+pack (synchronous);
        call :meth:`start_watches` to begin streaming — useful to install
        :attr:`on_event` consumers race-free between the two phases.
        """
        self._relist()
        if watch:
            self.start_watches()
        return self

    def start_watches(self) -> None:
        for path in _RESOURCES:
            t = threading.Thread(
                target=self._watch_loop, args=(path,), daemon=True
            )
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        # Sever in-flight streams: a watch reader blocked in readline()
        # would otherwise hold join() for up to the watch watchdog
        # (timeoutSeconds + grace, minutes).  The reader surfaces the
        # closed socket as a KubeAPIError, sees _stop, and exits.
        with self._lock:
            clients = list(self._active_clients)
        for c in clients:
            try:
                c.close()
            except Exception:  # noqa: BLE001 - best-effort teardown
                pass

    def wait_stopped(self, timeout: float | None = None) -> bool:
        """Block until :meth:`stop` is called (by a user or by a fatal
        watch-thread death — check :attr:`fatal` afterwards).  Supervisors
        serving this follower's snapshots wait on this: a stopped follower
        means the served state will only grow staler."""
        return self._stop.wait(timeout)

    def join(self, timeout: float | None = None) -> None:
        """Wait for the watch streams to end (tests: finite mock streams)."""
        for t in self._threads:
            t.join(timeout)

    def wait_synced(self, timeout: float | None = None) -> bool:
        return self._synced.wait(timeout)

    # -- state -------------------------------------------------------------
    def snapshot(self) -> ClusterSnapshot:
        """A consistent packed snapshot of the followed cluster."""
        with self._lock:
            if self._store is None:
                raise RuntimeError("follower not started")
            return self._store.snapshot()

    def fixture_view(self) -> dict:
        with self._lock:
            if self._store is None:
                raise RuntimeError("follower not started")
            return self._store.fixture_view()

    @property
    def errors(self) -> list[str]:
        """Recent transport/apply errors (each followed by a relist;
        bounded to the last 100)."""
        return list(self._errors)

    def stats(self) -> dict:
        """Retry/backoff/degradation counters (JSON-able), surfaced by
        the capacity service's ``info`` op and ``-doctor``: relist and
        watch failure totals, events applied, each stream's current
        backoff delay (0 when healthy), and the fatal state."""
        with self._lock:
            backoff = {
                p: round(d, 3)
                for p, d in self._backoff_s.items()
                if d > 0
            }
            recent, pdb_un, fatal = (
                len(self._errors), self._pdb_unavailable, self._fatal
            )
        return {
            # Views over the registry counters (same cells the scrape
            # renders); the dict shape is pinned by test_telemetry.py.
            **{name: int(c.value) for name, c in self._counters.items()},
            "backoff_s": backoff,
            "recent_errors": recent,
            "pdb_unavailable": pdb_un,
            "fatal": fatal,
        }

    def last_relist_age_s(self) -> float | None:
        """Seconds since the last successful full relist (``None`` before
        the first).  The ``/healthz`` freshness signal: a follower whose
        watches died can keep serving a stale snapshot indefinitely —
        this number is how a load balancer notices (the stats() dict
        shape is pinned, so the age rides its own accessor)."""
        with self._lock:
            t = self._last_relist_t
        return None if t is None else round(self._clock() - t, 3)

    def last_verified_age_s(self) -> float | None:
        """Seconds (on the injectable ``clock``) since the store was last
        verifiably synced — a completed relist or an applied watch event;
        ``None`` before the first relist.  The freshness input federation
        staleness math reads, so a bound like "stale after 10 s" is
        always computed against THIS clock (the stats() dict shape is
        pinned, so the age rides its own accessor, exactly like
        :meth:`last_relist_age_s`)."""
        with self._lock:
            t = self._last_verified_t
        return None if t is None else round(self._clock() - t, 3)

    def _bump(self, counter: str, n: int = 1) -> None:
        self._counters[counter].inc(n)

    def _next_backoff(self, path: str, prev: float | None) -> float:
        """One capped decorrelated-jitter backoff step, recorded so
        :meth:`stats` (and the backoff gauge) show the stream as
        backing off."""
        with self._lock:
            delay = decorrelated_jitter(
                self._backoff_rng, self._idle_backoff, prev, _BACKOFF_CAP_S
            )
            self._backoff_s[path] = delay
        self._m_backoff.set(delay, stream=path)
        return delay

    def _clear_backoff(self, path: str) -> None:
        with self._lock:
            self._backoff_s[path] = 0.0
        self._m_backoff.set(0.0, stream=path)

    @property
    def fatal(self) -> str | None:
        """Non-``None`` when a watch thread died on an unexpected error.

        Transport and apply failures relist-and-continue; anything else
        (notably :class:`~.oracle.ReferencePanic`, which reference mode
        deliberately re-raises where the Go process would have died) stops
        the follower and is recorded here — a dead sync loop must be
        *visible*, never a silently stale snapshot."""
        with self._lock:
            return self._fatal

    # -- internals ---------------------------------------------------------
    def _relist(self) -> None:
        """Full list of both resources → fresh store, under one lock hold."""
        client = self._factory()
        with self._lock:
            self._active_clients.add(client)
        try:
            # Registration races stop(): a client created after stop()
            # snapshotted the set would never be severed — re-check now
            # that we're visible, so either stop() closes us or we abort.
            if self._stop.is_set():
                raise KubeAPIError("follower stopping")
            fixture: dict = {"nodes": [], "pods": []}
            versions = {}
            for path, (kind, convert) in _RESOURCES.items():
                try:
                    items, version = client.list_with_version(path)
                except KubeAPIError as e:
                    if (
                        kind == "PodDisruptionBudget"
                        and e.status in (403, 404)
                    ):
                        # Policy API unreadable for this principal —
                        # degrade to a budget-less fixture (list_pdbs's
                        # rule); transport/5xx still fails the relist.
                        self._pdb_unavailable = True
                        continue
                    raise
                if kind == "PodDisruptionBudget":
                    self._pdb_unavailable = False
                fixture[_FIXTURE_KEYS[kind]] = [convert(o) for o in items]
                versions[path] = version
            store = ClusterStore(
                fixture,
                semantics=self._semantics,
                extended_resources=self._extended,
            )
        finally:
            with self._lock:
                self._active_clients.discard(client)
            client.close()
        with self._lock:
            self._store = store
            self._versions = versions
            self._epoch += 1
            self._last_relist_t = self._clock()
            self._last_verified_t = self._last_relist_t
        self._counters["relists"].inc()
        self._synced.set()
        # The swapped-in store may hold changes that never flowed through
        # per-object events (that's what a relist is FOR) — consumers
        # (e.g. the serve path's coalescer) must republish.
        if self.on_event is not None:
            self.on_event("*", "RELIST", {})

    def _watch_loop(self, path: str) -> None:
        try:
            self._watch_loop_inner(path)
        except Exception as e:  # noqa: BLE001 - a dead watch must be visible
            # Unexpected failure — notably ReferencePanic, which reference
            # mode re-raises where the Go process would have died, or a bug
            # in convert/apply.  Record it, mark the follower fatal, and
            # stop BOTH streams: serving ever-staler snapshots behind a
            # silently dead thread is the one unacceptable outcome.
            self._errors.append(f"{path}: fatal {type(e).__name__}: {e}")
            with self._lock:
                self._fatal = f"{path}: {type(e).__name__}: {e}"
            self.stop()

    def _watch_loop_inner(self, path: str) -> None:
        kind, convert = _RESOURCES[path]
        prev_delay: float | None = None
        failing_since: float | None = None
        while not self._stop.is_set():
            if kind == "PodDisruptionBudget" and self._pdb_unavailable:
                # The optional stream stands down instead of hammering a
                # 403-ing endpoint; relists keep retrying the list side.
                return
            with self._lock:
                version = self._versions.get(path)
                epoch = self._epoch
            try:
                stream_ended = self._consume_stream(
                    path, kind, convert, version, epoch
                )
            except (KubeAPIError, KubeConfigError, StoreError) as e:
                self._errors.append(f"{path}: {e}")
                self._bump("watch_failures")
                # Back off (client-go reflector cadence: base
                # idle_backoff, growing, capped at 30 s) with
                # decorrelated jitter — many followers recovering from
                # one outage must not relist in lockstep against the
                # shared apiserver — then relist (410 Gone / transport
                # loss / bad apply).  A failing relist retries forever
                # within the resync deadline — a transient outage must
                # never permanently stop the sync loop — and a
                # persistently rejected watch (e.g. RBAC grants list but
                # not watch) keeps the capped cadence, not one LIST per
                # second.
                if failing_since is None:
                    failing_since = time.monotonic()
                delay = self._next_backoff(path, prev_delay)
                prev_delay = delay
                while not self._stop.is_set():
                    self._stop.wait(delay)
                    if self._stop.is_set():
                        return
                    try:
                        self._relist()
                        # Data is fresh again (even if the WATCH is still
                        # being rejected) — the staleness clock resets.
                        failing_since = None
                        break
                    except (KubeAPIError, KubeConfigError) as e2:
                        self._errors.append(f"relist {path}: {e2}")
                        self._bump("relist_failures")
                        stale_for = time.monotonic() - failing_since
                        if stale_for > self._resync_deadline:
                            # Watch AND relist failing past the deadline:
                            # credentials expired unrefreshably, RBAC
                            # revoked, apiserver gone.  The served
                            # snapshot is stale and getting staler —
                            # go fatal (via _watch_loop) rather than
                            # retry silently forever.
                            raise RuntimeError(
                                f"resync failing for {stale_for:.0f}s "
                                f"(deadline {self._resync_deadline:.0f}s); "
                                f"last error: {e2}"
                            ) from e2
                        delay = self._next_backoff(path, delay)
                        prev_delay = delay
                continue
            prev_delay = None
            failing_since = None
            self._clear_backoff(path)
            if stream_ended:
                with self._lock:
                    unchanged = version == self._versions.get(path)
                if unchanged:
                    # Window ended with no progress (idle cluster, or a
                    # finite mock stream under test).
                    if self._stop_on_idle_window:
                        return
                    # Back off before re-watching so a server that closes
                    # instantly cannot drive a hot loop; interruptible.
                    self._stop.wait(self._idle_backoff)
                continue  # re-watch from the latest seen version

    def _consume_stream(self, path, kind, convert, version, epoch) -> bool:
        """Stream one watch window.  ``epoch`` is the relist generation this
        stream was started against: if a peer thread relists mid-flight
        (swapping in a store listed at a NEWER resourceVersion), this
        stream's remaining events are older than the store and must not be
        applied — the epoch check drops them and ends the stream, and the
        loop re-watches from the post-relist version."""
        client = self._factory()
        with self._lock:
            self._active_clients.add(client)
        try:
            if self._stop.is_set():  # registration/stop() race — see _relist
                return False
            for event in client.watch_events(
                path, resource_version=version or None
            ):
                if self._stop.is_set():
                    return False
                etype = event.get("type", "")
                obj = event.get("object") or {}
                if etype == "BOOKMARK":
                    rv = (obj.get("metadata") or {}).get("resourceVersion")
                    if rv and not self._set_version(path, rv, epoch):
                        return False  # stale epoch: abandon this stream
                    continue
                if etype == "ERROR":
                    code = obj.get("code")
                    raise KubeAPIError(
                        f"watch error event: {obj.get('message', obj)}",
                        status=code if isinstance(code, int) else None,
                    )
                rv = (obj.get("metadata") or {}).get("resourceVersion")
                if not self._apply(kind, etype, convert(obj), epoch):
                    return False  # stale epoch: abandon this stream
                if rv and not self._set_version(path, rv, epoch):
                    return False
            return True
        finally:
            with self._lock:
                self._active_clients.discard(client)
            client.close()

    def _set_version(self, path: str, rv: str, epoch: int) -> bool:
        """Advance the resume version — only if this stream is current."""
        with self._lock:
            if epoch != self._epoch:
                return False
            self._versions[path] = rv
        return True

    def _apply(self, kind: str, etype: str, obj: dict, epoch: int) -> bool:
        """Apply one event; False (no-op) if the stream's epoch is stale."""
        with self._lock:
            if epoch != self._epoch:
                return False
            store = self._store
            if kind == "Node":
                exists = store.has_node(obj.get("name", ""))
            elif kind == "PodDisruptionBudget":
                exists = store.has_pdb(
                    obj.get("namespace", ""), obj.get("name", "")
                )
            else:
                exists = store.has_pod(
                    obj.get("namespace", ""), obj.get("name", "")
                )
            # Upsert translation: relist races can replay ADDED for known
            # objects or DELETED for unknown ones; both are benign.
            if etype in ("ADDED", "MODIFIED"):
                etype = "MODIFIED" if exists else "ADDED"
            elif etype == "DELETED" and not exists:
                return True
            store.apply_event({"type": etype, "kind": kind, "object": obj})
            self._last_verified_t = self._clock()
        self._counters["events_applied"].inc()
        if self.on_event is not None:
            self.on_event(kind, etype, obj)
        return True
