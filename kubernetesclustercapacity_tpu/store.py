"""Incremental cluster store — the framework's informer analog.

The reference re-walks the entire apiserver on every invocation
(``1 + 2N + ΣP`` requests, SURVEY.md §3.4); real Kubernetes controllers
instead keep a *watch*-fed cache and apply object deltas.  This module is
that layer for the packed snapshot: a :class:`ClusterStore` holds the raw
node/pod state plus the dense arrays, and applies watch-style events —

    {"type": "ADDED"|"MODIFIED"|"DELETED",
     "kind": "Pod"|"Node",
     "object": <fixture-schema dict>}

— by recomputing only the affected node *rows* (O(pods-on-node) per pod
event, O(N) array reshape only when nodes join/leave), never the whole
cluster.  The invariant, enforced by tests on randomized event streams:
after any sequence of events the store's snapshot is element-identical to a
full :func:`~.snapshot.snapshot_from_fixture` repack of its state — under
either semantics, including the reference quirks (phantom rows re-homing
orphan pods, mod-2^64 usage wrap, parse-fail→0).
"""

from __future__ import annotations

import collections
import copy

import numpy as np

from kubernetesclustercapacity_tpu.oracle import reference as _oracle
from kubernetesclustercapacity_tpu.snapshot import (
    ClusterSnapshot,
    _effective_pod_resources,
    _clamp_i64,
    _strict_healthy,
    _strict_parse,
    _STRICT_TERMINATED,
    container_cpu_error_payloads as _container_cpu_error_payloads,
)
from kubernetesclustercapacity_tpu.utils.quantity import (
    cpu_parse_error_payload,
)

__all__ = ["StoreError", "ClusterStore"]

_INT_COLS = (
    "alloc_cpu_milli",
    "alloc_mem_bytes",
    "alloc_pods",
    "used_cpu_req_milli",
    "used_cpu_lim_milli",
    "used_mem_req_bytes",
    "used_mem_lim_bytes",
    "pods_count",
)


class StoreError(ValueError):
    """Malformed or inapplicable watch event."""


def _isolate(obj):
    """Deep copy of a JSON-shaped object — the store's aliasing barrier.

    Raw state must never alias caller objects (a caller mutating a pod
    dict after ``apply_event`` would silently corrupt the
    repack-equality invariant).  Watch/fixture objects are plain
    dict/list/scalar trees, for which a direct recursion is ~4x cheaper
    than ``copy.deepcopy``'s memo machinery — this is the per-event hot
    path of the ``-follow`` serve loop.  Anything exotic falls back to
    ``copy.deepcopy``; immutable scalars are shared, which is safe.
    """
    t = type(obj)
    if t is str:  # the overwhelmingly common leaf — test first
        return obj
    if t is dict:
        # Keys are isolated too: deepcopy copies keys, and a mutable-but-
        # hashable custom key must not reach through the barrier.
        return {_isolate(k): _isolate(v) for k, v in obj.items()}
    if t is list:
        return [_isolate(v) for v in obj]
    if t in (int, float, bool, type(None)):
        return obj
    return copy.deepcopy(obj)


def _pod_key(pod: dict) -> tuple[str, str]:
    return (pod.get("namespace", ""), pod.get("name", ""))


class ClusterStore:
    """Watch-fed packed snapshot with per-row incremental updates."""

    def __init__(
        self,
        fixture: dict,
        *,
        semantics: str = "reference",
        extended_resources: tuple[str, ...] = (),
    ):
        if semantics not in ("reference", "strict"):
            raise ValueError(f"unknown semantics {semantics!r}")
        if extended_resources and semantics != "strict":
            # The packer (snapshot_from_fixture) owns this rule; the store
            # re-raises it as a StoreError because its repack-equality
            # invariant would otherwise die later inside a recompute.
            raise StoreError(
                "extended resources require strict semantics"
            )
        self.semantics = semantics
        self.extended_resources = tuple(extended_resources)
        # Raw state, deep-copied: events must never alias caller objects.
        self._nodes: list[dict] = [_isolate(n) for n in fixture.get("nodes", [])]
        if semantics == "strict":
            # Strict mode matches pods to rows BY NAME, so duplicate or
            # empty names would diverge from _pack_strict (whose name index
            # is last-wins and whose "" row never matches): reject them,
            # preserving the element-identical-to-full-repack invariant.
            # (Reference mode keeps them: phantom-row semantics, Q4.)
            names = collections.Counter(
                n.get("name", "") for n in self._nodes
            )
            if names[""]:
                raise StoreError("strict mode requires non-empty node names")
            dups = sorted(x for x, c in names.items() if c > 1)
            if dups:
                raise StoreError(f"duplicate node names in fixture: {dups}")
        # PDBs ride along raw (no packed-array footprint): drain's budget
        # gate reads them from fixture_view, so a store-fed service must
        # not drop them on rematerialization.  Keyed by (namespace, name)
        # so watch events upsert/delete in O(1), like pods.
        self._pdbs: dict[tuple[str, str], dict] = {}
        for b in fixture.get("pdbs", []):
            key = self._validate_pdb(b)
            if key in self._pdbs:
                raise StoreError(f"duplicate PDB {key} in fixture")
            self._pdbs[key] = _isolate(b)
        self._pods: dict[tuple[str, str], dict] = {}
        self._pods_by_node: dict[str, dict[tuple[str, str], dict]] = {}
        for p in fixture.get("pods", []):
            p = _isolate(p)
            key = _pod_key(p)
            if key in self._pods:
                raise StoreError(f"duplicate pod {key} in fixture")
            self._pods[key] = p
            self._pods_by_node.setdefault(p.get("nodeName", ""), {})[key] = p

        n = len(self._nodes)
        # Columns may carry spare capacity beyond the live row count (rows
        # ADD by amortized doubling); every read slices to n_nodes.
        self._cols = {c: np.zeros(n, dtype=np.int64) for c in _INT_COLS}
        self._healthy = np.zeros(n, dtype=np.bool_)
        self._ext = {
            r: (np.zeros(n, dtype=np.int64), np.zeros(n, dtype=np.int64))
            for r in self.extended_resources
        }
        # The name a row *matches pods by*: the raw name in strict mode, the
        # NodeView name in reference mode ("" for phantom rows, Q4) — plus
        # inverted indices so a pod event touches its rows in O(1), not via
        # an O(N) name scan (the round-3 churn bottleneck), and node events
        # locate rows by raw name the same way.
        self._view_names: list[str] = [""] * n
        # Reference-mode transcript provenance, maintained per row so the
        # SERVED snapshot replays the same skip/codec-error lines a fresh
        # pack would (node_log assembles in row order; see snapshot()).
        self._node_events: list[tuple[str | None, str | None]] = [
            (None, None)
        ] * n  # (cpu_err_payload, skip_name)
        self._pod_errs: list[tuple[str, ...]] = [()] * n
        self._node_log_cache: list[tuple[str, str]] | None = None
        # Publication-form labels/taints, rebuilt PER ROW on recompute
        # (node objects are replaced wholesale, never mutated in place).
        # snapshot() then costs outer list copies only — per-publish
        # Python loops over 10k rows starved the GIL against the event
        # thread and collapsed sustained churn throughput ~8x.
        self._labels_pub: list[dict] = [{}] * n
        self._taints_pub: list[list] = [[]] * n
        self._rows_by_view: dict[str, set[int]] = {"": set(range(n))}
        self._rows_by_raw: dict[str, set[int]] = {}
        for i, node in enumerate(self._nodes):
            self._rows_by_raw.setdefault(node.get("name", ""), set()).add(i)
        for i in range(n):
            self._recompute_row(i)
            self._refresh_pub_row(i, self._nodes[i])

    # -- public ------------------------------------------------------------
    @property
    def n_nodes(self) -> int:
        return len(self._nodes)

    def has_node(self, name: str) -> bool:
        return bool(self._rows_by_raw.get(name))

    def has_pod(self, namespace: str, name: str) -> bool:
        return (namespace, name) in self._pods

    def has_pdb(self, namespace: str, name: str) -> bool:
        return (namespace, name) in self._pdbs

    def fixture_view(self) -> dict:
        """Current raw state in fixture schema (deep copy)."""
        out = {"nodes": self._nodes, "pods": list(self._pods.values())}
        if self._pdbs:
            out["pdbs"] = list(self._pdbs.values())
        return _isolate(out)

    def snapshot(self) -> ClusterSnapshot:
        """A packed snapshot decoupled from the store's raw state.

        Numeric arrays are copied; names/provenance entries are immutable
        (strings/tuples); labels/taints are outer-copied lists over
        per-row dicts the store REPLACES (never mutates) on node events —
        so no caller mutation can reach raw state or poison repacks.  A
        caller that mutates a returned snapshot's label dicts in place
        can confuse a LATER snapshot's labels (they share row objects
        until that row's node changes); treat snapshots as read-only.
        """
        # Reference mode reports the NodeView name — "" for phantom rows,
        # exactly what the Go slice holds (Q4); strict reports raw names.
        n = len(self._nodes)
        node_log: list[tuple[str, str]] = []
        pod_cpu_errs: list[list[str]] = []
        if self.semantics == "reference":
            if self._node_log_cache is None:
                cache: list[tuple[str, str]] = []
                for cpu_err, skip_name in self._node_events:
                    if cpu_err is not None:
                        cache.append(("cpu_err", cpu_err))
                    if skip_name is not None:
                        cache.append(("skip", skip_name))
                self._node_log_cache = cache
            node_log = list(self._node_log_cache)
            pod_cpu_errs = list(self._pod_errs)
        # Outer-copied lists over per-row publication objects: the store
        # never mutates an inner dict/list in place (rows rebuild them
        # wholesale), so the returned snapshot can never read through to
        # raw state.  Inner objects ARE shared between snapshots — a
        # caller mutating one snapshot's labels can confuse a later
        # snapshot, never the store (fixture_view/repacks read raw state).
        return ClusterSnapshot(
            names=list(self._view_names),
            semantics=self.semantics,
            extended={
                r: (a[:n].copy(), u[:n].copy())
                for r, (a, u) in self._ext.items()
            },
            labels=list(self._labels_pub),
            taints=list(self._taints_pub),
            node_log=node_log,
            pod_cpu_errs=pod_cpu_errs,
            healthy=self._healthy[:n].copy(),
            **{c: self._cols[c][:n].copy() for c in _INT_COLS},
        )

    def apply(self, events: list[dict]) -> ClusterSnapshot:
        """Apply watch events in order; returns the updated snapshot.

        Events are validated before any mutation of the failing event is
        applied — a bad event raises :class:`StoreError` and leaves the
        store at the state after the last good event.
        """
        for ev in events:
            self.apply_event(ev)
        return self.snapshot()

    def apply_event(self, event: dict) -> None:
        etype = event.get("type")
        kind = event.get("kind")
        obj = event.get("object")
        if etype not in ("ADDED", "MODIFIED", "DELETED"):
            raise StoreError(f"unknown event type {etype!r}")
        if not isinstance(obj, dict):
            raise StoreError("event has no object")
        try:
            obj = _isolate(obj)
        except RecursionError as e:
            # A self-referential object is a malformed event, not a crash:
            # keep apply_event's "bad event raises StoreError" contract
            # (copy.deepcopy would have memoized the cycle; the fast
            # copier declines it instead).
            raise StoreError(f"cyclic event object: {e}") from e
        if kind == "Pod":
            self._apply_pod(etype, obj)
        elif kind == "Node":
            self._apply_node(etype, obj)
        elif kind == "PodDisruptionBudget":
            self._apply_pdb(etype, obj)
        else:
            raise StoreError(f"unknown event kind {kind!r}")

    def _apply_pdb(self, etype: str, obj: dict) -> None:
        """PDB events touch only the raw side (no packed arrays): upsert
        or delete by (namespace, name); drain reads the result from
        fixture_view.  A DELETED event only needs the key — real watch
        streams send the full last-known object, but a key-only delete
        (the service ``update`` op's natural shape) must not fail the
        spec-field validation."""
        if etype == "DELETED":
            self._pdbs.pop(
                (str(obj.get("namespace", "")), str(obj.get("name", ""))),
                None,
            )
        else:
            self._pdbs[self._validate_pdb(obj)] = obj

    # -- validation (before ANY mutation: a malformed object must never
    # enter raw state, or it would poison every later recompute AND the
    # full-repack invariant) ----------------------------------------------
    def _validate_pod(self, pod: dict) -> tuple[str, str]:
        try:
            key = _pod_key(pod)
            hash(key)
            hash(pod.get("nodeName", ""))  # it indexes _pods_by_node
            # The phase feeds frozenset membership on every recompute —
            # an unhashable phase must be rejected HERE, not crash later.
            phase = pod.get("phase")
            phase in _STRICT_TERMINATED  # noqa: B015 - hashability probe
            if self.semantics == "reference":
                _oracle.pod_requests_limits([pod])
            else:
                _effective_pod_resources(pod, self.extended_resources)
        except Exception as e:
            raise StoreError(f"malformed pod object: {e}") from e
        return key

    def _validate_pdb(self, pdb: dict) -> tuple[str, str]:
        """Run the budget arithmetic once against a synthetic pod in the
        budget's namespace — the ONE definition of PDB well-formedness
        (``pdb.budget_statuses``) owns the rules — plus a structural
        selector check (``pdb.validate_selector``): the probe pod
        carries no labels, so a non-empty ``matchLabels`` short-circuits
        ``_selector_matches`` before ``matchExpressions`` are ever
        evaluated, and a malformed operator would sail through to poison
        every later ``drain``/``budget_statuses`` read.  The structural
        check evaluates every expression unconditionally, so malformed
        selectors fail at admission."""
        from kubernetesclustercapacity_tpu.pdb import (
            budget_statuses,
            validate_selector,
        )

        try:
            key = (str(pdb.get("namespace", "")), str(pdb.get("name", "")))
            validate_selector(pdb.get("selector") or {})
            probe = {
                "namespace": key[0], "name": "", "nodeName": "probe",
                "phase": "Running", "labels": {},
            }
            budget_statuses({"pdbs": [pdb], "pods": [probe]})
        except Exception as e:
            raise StoreError(f"malformed PDB object: {e}") from e
        return key

    def _validate_node(self, node: dict) -> None:
        try:
            if self.semantics == "reference":
                # Runs the reference health check too: its <4-conditions
                # ReferencePanic (Q3) surfaces as-is, pre-mutation, where
                # the reference process would simply have died.
                _oracle.healthy_nodes({"nodes": [node]})
            else:
                allocatable = node.get("allocatable", {})
                for k in ("cpu", "memory", "pods", *self.extended_resources):
                    _strict_parse(allocatable.get(k), milli=(k == "cpu"))
                _strict_healthy(node.get("conditions", []))
        except _oracle.ReferencePanic:
            raise
        except Exception as e:
            raise StoreError(f"malformed node object: {e}") from e

    # -- pods --------------------------------------------------------------
    def _apply_pod(self, etype: str, pod: dict) -> None:
        key = self._validate_pod(pod)
        old = self._pods.get(key)
        if etype == "ADDED" and old is not None:
            raise StoreError(f"pod {key} already exists")
        if etype in ("MODIFIED", "DELETED") and old is None:
            raise StoreError(f"pod {key} not found")

        touched = set()
        if old is not None:
            old_node = old.get("nodeName", "")
            del self._pods_by_node[old_node][key]
            touched.add(old_node)
        if etype == "DELETED":
            del self._pods[key]
        else:
            new_node = pod.get("nodeName", "")
            self._pods[key] = pod
            self._pods_by_node.setdefault(new_node, {})[key] = pod
            touched.add(new_node)
        for node_name in touched:
            for i in self._rows_matching(node_name):
                self._recompute_row(i)

    def _rows_matching(self, node_name: str) -> list[int]:
        """Rows whose pod-match name equals ``node_name`` (indexed, O(1)).

        In reference mode every phantom row matches ``""`` — an orphan-pod
        event touches all of them (the degenerate field selector, Q4).
        """
        return list(self._rows_by_view.get(node_name, ()))

    def _set_view_name(self, i: int, name: str) -> None:
        """Row view-name write-through that keeps the inverted index true."""
        old = self._view_names[i]
        if old == name:
            return
        rows = self._rows_by_view.get(old)
        if rows is not None:
            rows.discard(i)
        self._rows_by_view.setdefault(name, set()).add(i)
        self._view_names[i] = name

    def _rebuild_indices(self) -> None:
        """Full index rebuild — row indices shifted (node DELETE compaction)."""
        self._rows_by_view = {}
        self._rows_by_raw = {}
        for i, (node, view) in enumerate(zip(self._nodes, self._view_names)):
            self._rows_by_raw.setdefault(node.get("name", ""), set()).add(i)
            self._rows_by_view.setdefault(view, set()).add(i)

    # -- nodes -------------------------------------------------------------
    def _apply_node(self, etype: str, node: dict) -> None:
        name = node.get("name", "")
        if etype in ("ADDED", "MODIFIED"):
            self._validate_node(node)
            if self.semantics == "strict" and not name:
                raise StoreError("strict mode requires non-empty node names")
        idx = sorted(self._rows_by_raw.get(name, ()))
        if etype == "ADDED":
            if idx:
                raise StoreError(f"node {name!r} already exists")
            self._append_row()
            self._nodes.append(node)
            i = len(self._nodes) - 1
            self._rows_by_raw.setdefault(name, set()).add(i)
            self._recompute_row(i)
            self._refresh_pub_row(i, node)
        elif etype == "MODIFIED":
            if not idx:
                raise StoreError(f"node {name!r} not found")
            for i in idx:
                self._nodes[i] = node
                self._recompute_row(i)
                self._refresh_pub_row(i, node)
        else:  # DELETED
            if not idx:
                raise StoreError(f"node {name!r} not found")
            n = len(self._nodes)
            keep = np.ones(n, dtype=bool)
            keep[idx] = False
            for c in _INT_COLS:
                self._cols[c] = self._cols[c][:n][keep]
            self._healthy = self._healthy[:n][keep]
            self._ext = {
                r: (a[:n][keep], u[:n][keep])
                for r, (a, u) in self._ext.items()
            }
            self._nodes = [nd for i, nd in enumerate(self._nodes) if keep[i]]
            self._view_names = [
                v for i, v in enumerate(self._view_names) if keep[i]
            ]
            self._node_events = [
                e for i, e in enumerate(self._node_events) if keep[i]
            ]
            self._pod_errs = [
                e for i, e in enumerate(self._pod_errs) if keep[i]
            ]
            self._labels_pub = [
                e for i, e in enumerate(self._labels_pub) if keep[i]
            ]
            self._taints_pub = [
                e for i, e in enumerate(self._taints_pub) if keep[i]
            ]
            self._node_log_cache = None
            self._rebuild_indices()

    def _append_row(self) -> None:
        """Grow columns by amortized doubling (per-ADD ``np.append`` was
        O(N) — quadratic on relist-scale joins); the new row starts zeroed
        with view name ``""`` and is recomputed by the caller."""
        n = len(self._nodes)
        cap = self._healthy.shape[0]
        if n >= cap:
            pad = max(16, cap)
            self._cols = {
                c: np.concatenate([a, np.zeros(pad, a.dtype)])
                for c, a in self._cols.items()
            }
            self._healthy = np.concatenate(
                [self._healthy, np.zeros(pad, np.bool_)]
            )
            self._ext = {
                r: (
                    np.concatenate([a, np.zeros(pad, np.int64)]),
                    np.concatenate([u, np.zeros(pad, np.int64)]),
                )
                for r, (a, u) in self._ext.items()
            }
        self._view_names.append("")
        self._node_events.append((None, None))
        self._pod_errs.append([])
        self._labels_pub.append({})
        self._taints_pub.append([])
        self._node_log_cache = None
        self._rows_by_view.setdefault("", set()).add(n)

    # -- row packing (the single source of per-row truth) ------------------
    def _node_pods(self, match_name: str) -> list[dict]:
        return list(self._pods_by_node.get(match_name, {}).values())

    def _recompute_row(self, i: int) -> None:
        raw = self._nodes[i]
        if self.semantics == "reference":
            self._recompute_row_reference(i, raw)
        else:
            self._recompute_row_strict(i, raw)

    def _recompute_row_reference(self, i: int, raw: dict) -> None:
        # Single-node oracle walk: health check (incl. the <4-conditions
        # panic), reference codecs, phantom zeroing — identical to
        # _pack_reference's per-node step by construction.
        view = _oracle.healthy_nodes({"nodes": [raw]})[0]
        pods = [
            p
            for p in self._node_pods(view.name)
            if _oracle._survives_field_selector(p)
        ]
        cpu_lim, cpu_req, mem_lim, mem_req = _oracle.pod_requests_limits(pods)
        # Transcript provenance (same events _pack_reference records): the
        # node's cpu codec error, its skip line when unhealthy (with the
        # REAL name — the phantom row keeps ""), and its pods' container
        # codec errors in walk order, limits before requests (:279-284).
        allocatable = raw.get("allocatable", {})
        cpu_err = cpu_parse_error_payload(allocatable.get("cpu", "0"))
        skip = (
            None
            if _oracle.node_is_healthy_reference(raw)
            else raw.get("name", "")
        )
        new_events = (cpu_err, skip)
        if new_events != self._node_events[i]:
            self._node_events[i] = new_events
            self._node_log_cache = None  # row order changed the flat log
        self._pod_errs[i] = tuple(_container_cpu_error_payloads(pods))
        c = self._cols
        c["alloc_cpu_milli"][i] = _clamp_i64(view.allocatable_cpu)
        c["alloc_mem_bytes"][i] = _clamp_i64(view.allocatable_memory)
        c["alloc_pods"][i] = view.allocatable_pods
        c["used_cpu_req_milli"][i] = _clamp_i64(cpu_req)
        c["used_cpu_lim_milli"][i] = _clamp_i64(cpu_lim)
        c["used_mem_req_bytes"][i] = mem_req
        c["used_mem_lim_bytes"][i] = mem_lim
        c["pods_count"][i] = len(pods)
        self._healthy[i] = bool(view.name)
        self._set_view_name(i, view.name)

    def _refresh_pub_row(self, i: int, raw: dict) -> None:
        """Rebuild row ``i``'s publication-form labels/taints (fresh inner
        objects — returned snapshots must never alias raw state).  Called
        only from NODE-driven paths: pod events cannot change labels or
        taints, and rebuilding them per pod event would put allocation
        back on the churn hot path."""
        self._labels_pub[i] = dict(raw.get("labels", {}))
        self._taints_pub[i] = [dict(t) for t in raw.get("taints", [])]

    def _recompute_row_strict(self, i: int, raw: dict) -> None:
        name = raw.get("name", "")
        allocatable = raw.get("allocatable", {})
        c = self._cols
        c["alloc_cpu_milli"][i] = _strict_parse(allocatable.get("cpu"), milli=True)
        c["alloc_mem_bytes"][i] = _strict_parse(allocatable.get("memory"))
        c["alloc_pods"][i] = _strict_parse(allocatable.get("pods"))
        self._healthy[i] = _strict_healthy(raw.get("conditions", []))
        self._set_view_name(i, name)

        totals = dict.fromkeys(
            ("cpu_req", "cpu_lim", "mem_req", "mem_lim", "count"), 0
        )
        ext_used = dict.fromkeys(self.extended_resources, 0)
        for p in self._node_pods(name):
            if p.get("phase") in _STRICT_TERMINATED:
                continue
            totals["count"] += 1
            eff = _effective_pod_resources(p, self.extended_resources)
            for k in ("cpu_req", "cpu_lim", "mem_req", "mem_lim"):
                totals[k] += eff[k]
            for r in self.extended_resources:
                ext_used[r] += eff["ext"][r]
        c["used_cpu_req_milli"][i] = totals["cpu_req"]
        c["used_cpu_lim_milli"][i] = totals["cpu_lim"]
        c["used_mem_req_bytes"][i] = totals["mem_req"]
        c["used_mem_lim_bytes"][i] = totals["mem_lim"]
        c["pods_count"][i] = totals["count"]
        for r in self.extended_resources:
            self._ext[r][0][i] = _strict_parse(allocatable.get(r))
            self._ext[r][1][i] = ext_used[r]
