"""Sharded capacity sweeps: the fit kernel laid out over a device mesh.

Two equivalent paths, both bit-exact against the single-device kernel:

* :func:`sweep_gspmd` — the idiomatic JAX path: inputs are ``device_put`` with
  ``NamedSharding``s (nodes → ``"node"`` axis, scenarios → ``"scenario"``
  axis) and the already-jitted kernel runs under GSPMD, letting XLA insert
  the cross-device reduction for the node-sharded sum.
* :func:`sweep_shard_map` — explicit SPMD: per-device shards compute local
  partial replica sums and an explicit ``lax.psum`` over the ``"node"`` axis
  reduces them over ICI.  This is the path whose collective schedule we
  control (and the one the multi-chip dry-run exercises).

Padding: node arrays pad with zero rows — a zero row yields fit 0 in both
modes (alloc ≤ used guards to 0, then the Q1 cap rewrites ``0 ≥ 0`` to
``0 − 0``) — and scenario arrays pad with a harmless ``(1 milli, 1 byte)``
probe whose outputs are sliced off.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 exposes shard_map at top level
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map  # type: ignore

from kubernetesclustercapacity_tpu.ops.fit import (
    fit_per_node,
    sweep_grid,
    sweep_grid_grouped,
)
from kubernetesclustercapacity_tpu.parallel.mesh import (
    MeshPlan,
    NODE_AXIS,
    SCENARIO_AXIS,
)

__all__ = [
    "sweep_gspmd",
    "sweep_gspmd_grouped",
    "sweep_shard_map",
    "stage_gspmd_arrays",
    "stage_gspmd_grouped_arrays",
]


def _pad_node_arrays(arrays: tuple, n_padded: int) -> tuple:
    """Zero-pad the 7 snapshot arrays along the node axis."""
    out = []
    for a in arrays:
        a = np.asarray(a)
        pad = n_padded - a.shape[0]
        out.append(np.pad(a, (0, pad)) if pad else a)
    return tuple(out)


def _pad_scenarios(cpu_reqs, mem_reqs, replicas, s_padded: int):
    cpu_reqs = np.asarray(cpu_reqs, dtype=np.int64)
    mem_reqs = np.asarray(mem_reqs, dtype=np.int64)
    replicas = np.asarray(replicas, dtype=np.int64)
    pad = s_padded - cpu_reqs.shape[0]
    if pad:
        cpu_reqs = np.pad(cpu_reqs, (0, pad), constant_values=1)
        mem_reqs = np.pad(mem_reqs, (0, pad), constant_values=1)
        replicas = np.pad(replicas, (0, pad), constant_values=0)
    return cpu_reqs, mem_reqs, replicas


def stage_gspmd_arrays(plan: MeshPlan, snapshot) -> tuple:
    """A snapshot's 7 node arrays, padded to the plan and ``device_put``
    with the node-axis ``NamedSharding`` — cached in the device cache per
    ``(snapshot, mesh, padded-N)`` so repeat sharded sweeps skip the
    host→device scatter entirely (the sharded analog of the
    single-device resident cache)."""
    from kubernetesclustercapacity_tpu import devcache

    n = snapshot.n_nodes
    n_padded = plan.pad_nodes(n)
    mesh = plan.mesh

    def build() -> tuple:
        arrays = _pad_node_arrays(
            (
                snapshot.alloc_cpu_milli,
                snapshot.alloc_mem_bytes,
                snapshot.alloc_pods,
                snapshot.used_cpu_req_milli,
                snapshot.used_mem_req_bytes,
                snapshot.pods_count,
                snapshot.healthy,
            ),
            n_padded,
        )
        sharding = NamedSharding(mesh, P(NODE_AXIS))
        return tuple(jax.device_put(a, sharding) for a in arrays)

    return devcache.CACHE.get(snapshot, ("gspmd", mesh, n_padded), build)


def stage_gspmd_grouped_arrays(plan: MeshPlan, grouped) -> tuple:
    """A grouped snapshot's 7 shape columns + counts, padded to the plan
    and ``device_put`` with the node-axis ``NamedSharding`` — the
    heterogeneous-tail answer to ROADMAP item 1: once shape compression
    has collapsed the degenerate bulk, the remaining truly-distinct rows
    shard across the GSPMD mesh.  Cached per ``(snapshot, mesh,
    padded-G)`` under the ``"gspmd_grouped"`` form (zero-count padded
    rows contribute nothing to the weighted sum)."""
    from kubernetesclustercapacity_tpu import devcache

    g = grouped.n_groups
    g_padded = plan.pad_nodes(g)
    mesh = plan.mesh

    def build() -> tuple:
        arrays = _pad_node_arrays(
            (
                grouped.alloc_cpu_milli,
                grouped.alloc_mem_bytes,
                grouped.alloc_pods,
                grouped.used_cpu_req_milli,
                grouped.used_mem_req_bytes,
                grouped.pods_count,
                grouped.healthy,
                grouped.count,
            ),
            g_padded,
        )
        sharding = NamedSharding(mesh, P(NODE_AXIS))
        return tuple(jax.device_put(a, sharding) for a in arrays)

    return devcache.CACHE.get(
        grouped.snapshot, ("gspmd_grouped", mesh, g_padded), build
    )


def sweep_gspmd_grouped(
    plan: MeshPlan,
    grouped,
    cpu_reqs,
    mem_reqs,
    replicas,
    *,
    mode: str = "reference",
    node_mask=None,
):
    """GSPMD sweep over node-shape groups: the ``[G]`` shape columns
    shard over the mesh's node axis, scenarios over the scenario axis,
    and the count-weighted reduction runs under GSPMD — XLA inserts the
    cross-device sum exactly as it does for the ungrouped
    :func:`sweep_gspmd`.  ``node_mask`` (``[N]`` bool over the PARENT
    snapshot's nodes) folds into per-group effective counts, which then
    replace the staged base counts for this call.  Bit-exact against the
    unsharded grouped kernel (zero-padded rows carry count 0).
    """
    s = np.asarray(cpu_reqs).shape[0]
    mesh = plan.mesh
    staged = stage_gspmd_grouped_arrays(plan, grouped)
    node_dev, counts_dev = staged[:7], staged[7]
    if node_mask is not None:
        counts = grouped.effective_counts(node_mask)
        pad = int(np.asarray(staged[0]).shape[0]) - grouped.n_groups
        counts = np.pad(counts, (0, pad)) if pad else counts
        counts_dev = jax.device_put(
            counts, NamedSharding(mesh, P(NODE_AXIS))
        )
    scen_sharding = NamedSharding(mesh, P(SCENARIO_AXIS))
    cpu_p, mem_p, rep_p = _pad_scenarios(
        cpu_reqs, mem_reqs, replicas, plan.pad_scenarios(s)
    )
    cpu_d = jax.device_put(cpu_p, scen_sharding)
    mem_d = jax.device_put(mem_p, scen_sharding)
    rep_d = jax.device_put(rep_p, scen_sharding)

    totals, sched = sweep_grid_grouped(
        *node_dev, counts_dev, cpu_d, mem_d, rep_d, mode=mode
    )
    return np.asarray(totals)[:s], np.asarray(sched)[:s]


def sweep_gspmd(
    plan: MeshPlan,
    snapshot_arrays: tuple,
    cpu_reqs,
    mem_reqs,
    replicas,
    *,
    mode: str = "reference",
    snapshot=None,
):
    """GSPMD sweep: sharding annotations in, XLA chooses the collectives.

    ``snapshot`` (optional) names the ClusterSnapshot the arrays came
    from; when given, the padded+sharded node arrays come from the
    device cache (:func:`stage_gspmd_arrays`) instead of being scattered
    host→device per call.
    """
    s = np.asarray(cpu_reqs).shape[0]
    n = np.asarray(snapshot_arrays[0]).shape[0]
    mesh = plan.mesh
    scen_sharding = NamedSharding(mesh, P(SCENARIO_AXIS))
    if snapshot is not None:
        node_dev = stage_gspmd_arrays(plan, snapshot)
    else:
        node_arrays = _pad_node_arrays(snapshot_arrays, plan.pad_nodes(n))
        node_sharding = NamedSharding(mesh, P(NODE_AXIS))
        node_dev = tuple(
            jax.device_put(a, node_sharding) for a in node_arrays
        )
    cpu_p, mem_p, rep_p = _pad_scenarios(
        cpu_reqs, mem_reqs, replicas, plan.pad_scenarios(s)
    )
    cpu_d = jax.device_put(cpu_p, scen_sharding)
    mem_d = jax.device_put(mem_p, scen_sharding)
    rep_d = jax.device_put(rep_p, scen_sharding)

    totals, sched = sweep_grid(*node_dev, cpu_d, mem_d, rep_d, mode=mode)
    return np.asarray(totals)[:s], np.asarray(sched)[:s]


def sweep_shard_map(
    plan: MeshPlan,
    snapshot_arrays: tuple,
    cpu_reqs,
    mem_reqs,
    replicas,
    *,
    mode: str = "reference",
):
    """Explicit-SPMD sweep: local partial sums + ``psum`` over the node axis.

    Each device holds a ``[N/node_shards]`` slice of every snapshot array and
    a ``[S/scenario_shards]`` slice of the grid; it computes
    ``fits[s_local, n_local]``, reduces locally over its node slice, and one
    ``psum`` over ``"node"`` (ICI) produces replicated per-scenario totals.
    """
    s = np.asarray(cpu_reqs).shape[0]
    n = np.asarray(snapshot_arrays[0]).shape[0]
    node_arrays = _pad_node_arrays(snapshot_arrays, plan.pad_nodes(n))
    cpu_p, mem_p, rep_p = _pad_scenarios(
        cpu_reqs, mem_reqs, replicas, plan.pad_scenarios(s)
    )

    totals, sched = _compiled_shard_fn(plan.mesh, mode)(
        *[jnp.asarray(a) for a in node_arrays],
        jnp.asarray(cpu_p),
        jnp.asarray(mem_p),
        jnp.asarray(rep_p),
    )
    return np.asarray(totals)[:s], np.asarray(sched)[:s]


@lru_cache(maxsize=None)
def _compiled_shard_fn(mesh, mode: str):
    """Jitted shard_map sweep, cached per (mesh, mode).

    ``Mesh`` is hashable, so repeated sweeps on the same mesh hit the jit
    cache instead of re-tracing a fresh closure each call (the intended
    service pattern: one mesh, many sweeps).
    """
    node_spec = P(NODE_AXIS)
    scen_spec = P(SCENARIO_AXIS)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(node_spec,) * 7 + (scen_spec,) * 3,
        out_specs=(scen_spec, scen_spec),
    )
    def _shard_fn(ac, am, ap, uc, um, pc, h, cr, mr, rep):
        local_fits = jax.vmap(
            lambda c, m: fit_per_node(ac, am, ap, uc, um, pc, h, c, m, mode=mode)
        )(cr, mr)
        partial_totals = jnp.sum(local_fits, axis=1)  # [s_local]
        totals = jax.lax.psum(partial_totals, NODE_AXIS)
        return totals, totals >= rep

    return jax.jit(_shard_fn)
