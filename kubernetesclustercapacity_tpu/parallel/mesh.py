"""Mesh construction: how devices are arranged for capacity sweeps.

Axis semantics:

* ``"scenario"`` — shards the what-if grid.  No cross-device traffic at all
  (each device owns complete results for its scenarios); scales over DCN as
  well as ICI, so multi-host sweeps partition here first.
* ``"node"``     — shards the cluster's node axis.  Each device computes
  partial per-scenario replica sums over its node shard; one int64 ``psum``
  per sweep reduces them over ICI.  Use when a single cluster snapshot is too
  big for one device's HBM (≥ millions of nodes) or to cut per-device work
  for latency.  Proven at that scale: ``tests/test_parallel.py::
  TestMillionNodeScale`` pins both sharded paths bit-exact on a 1M-node
  snapshot — shard_map over a pure node-axis (1×8) mesh, GSPMD over a
  mixed (2×4) mesh — and ``bench.py`` records the single-chip 1M-node
  sweep (``nodes_1m_per_sweep_ms``).

For the 10k-node × 1k-scenario north-star on a v4-8, scenario-only sharding
is optimal (zero collectives); the node axis exists for the scale beyond.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["MeshPlan", "make_mesh"]

SCENARIO_AXIS = "scenario"
NODE_AXIS = "node"


@dataclass(frozen=True)
class MeshPlan:
    """A mesh plus the padding arithmetic sweeps need to fit on it."""

    mesh: Mesh

    @property
    def scenario_shards(self) -> int:
        return self.mesh.shape[SCENARIO_AXIS]

    @property
    def node_shards(self) -> int:
        return self.mesh.shape[NODE_AXIS]

    def pad_scenarios(self, s: int) -> int:
        """Padded scenario count (next multiple of the scenario axis)."""
        k = self.scenario_shards
        return -(-s // k) * k

    def pad_nodes(self, n: int) -> int:
        k = self.node_shards
        return -(-n // k) * k


def make_mesh(
    scenario_parallel: int | None = None,
    node_parallel: int = 1,
    *,
    devices: list | None = None,
) -> MeshPlan:
    """Build a ``(scenario, node)`` mesh over the available devices.

    Defaults to all devices on the scenario axis (the collective-free
    layout).  ``scenario_parallel × node_parallel`` must cover the device
    count exactly; pass explicit values to trade grid-parallelism for
    node-shard parallelism.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    n_dev = len(devices)
    if node_parallel < 1:
        raise ValueError(f"node_parallel must be >= 1, got {node_parallel}")
    if scenario_parallel is None:
        scenario_parallel = n_dev // node_parallel
    if scenario_parallel * node_parallel != n_dev:
        raise ValueError(
            f"mesh {scenario_parallel}x{node_parallel} != {n_dev} devices"
        )
    grid = np.array(devices).reshape(scenario_parallel, node_parallel)
    return MeshPlan(mesh=Mesh(grid, (SCENARIO_AXIS, NODE_AXIS)))
