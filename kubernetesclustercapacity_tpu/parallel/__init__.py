"""Distributed layer: device meshes, shardings, and collective reductions.

The reference has no parallelism at all — one goroutine, one sequential node
loop (SURVEY.md §2.3).  This package is its TPU-native counterpart: the sweep
is laid out over a 2-D ``jax.sharding.Mesh`` with a **scenario** axis (the
embarrassingly-parallel what-if grid — the data-parallel analog) and a
**node** axis (cluster nodes sharded across devices with a ``psum`` reduction
of per-shard replica counts — the sequence-parallel analog).  Collectives are
XLA-inserted and ride ICI within a slice; multi-host deployments extend the
same mesh over DCN via ``jax.distributed.initialize``.
"""

from kubernetesclustercapacity_tpu.parallel.mesh import (  # noqa: F401
    MeshPlan,
    make_mesh,
)
from kubernetesclustercapacity_tpu.parallel.sweep import (  # noqa: F401
    sweep_gspmd,
    sweep_shard_map,
)
from kubernetesclustercapacity_tpu.parallel import multihost  # noqa: F401
