"""Multi-host sweeps: the DCN story (SURVEY.md §5 "distributed backend").

The reference has no distributed anything (§2.3); this framework's scaling
axes are *scenarios* and *nodes*, and they map onto TPU pod networks the
standard way:

* **scenario axis over DCN** — embarrassingly parallel: each host owns a
  contiguous block of the what-if grid and computes complete results for
  it.  Zero cross-host collectives in the compute; one optional
  ``process_allgather`` at the end if every host wants the full result.
* **node axis over ICI** — within a host's chips, the ``psum`` replica
  reduction of :func:`..parallel.sweep.sweep_shard_map` rides the
  intra-slice interconnect.

Launch recipe (one process per host, standard JAX multi-process SPMD)::

    # on every host, same program:
    from kubernetesclustercapacity_tpu.parallel import multihost
    multihost.initialize(coordinator_address="host0:8476",
                         num_processes=H, process_id=h)   # no-op when H==1
    totals, sched = multihost.sweep_multihost(snapshot_arrays, grid)

Everything here degrades to single-process semantics when
``jax.process_count() == 1``, so the same program runs on a laptop, one
TPU host, or a pod — and the test suite exercises the single-process path
on the virtual CPU mesh.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubernetesclustercapacity_tpu.ops.fit import sweep_grid, sweep_grid_multi
from kubernetesclustercapacity_tpu.parallel.mesh import SCENARIO_AXIS

__all__ = [
    "initialize",
    "sweep_multihost",
    "sweep_multihost_multi",
    "scenario_block",
]


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    **kwargs,
) -> bool:
    """Join the multi-process JAX runtime.

    Returns True when distributed mode was initialized.  Call once per
    process before any other JAX use.

    * ``num_processes=1`` → single-process run, a clean no-op returning
      False whatever else is set (the same launch recipe runs unchanged
      on a laptop, per the module docstring).
    * no arguments at all → also a no-op.  (Divergence from upstream,
      documented: ``jax.distributed.initialize()`` with no args attempts
      cluster AUTO-DETECTION — request that explicitly here, e.g.
      ``initialize(cluster_detection_method="deprecated_slurm")`` or by
      passing the pod's coordinator arguments — so library users on
      single machines are not greeted with a failed detection.)
    * anything else → passed straight through to
      ``jax.distributed.initialize``; in particular a partial argument
      set (coordinator WITHOUT num_processes, ...) is no longer a silent
      no-op — upstream validates, auto-completes, or raises.
    """
    if num_processes == 1:
        return False  # explicitly single-process
    if (
        num_processes is None
        and coordinator_address is None
        and process_id is None
        and not kwargs
    ):
        return False  # nothing requested
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        **kwargs,
    )
    return True


def scenario_block(total: int, process_id: int, process_count: int) -> tuple[int, int]:
    """The [start, stop) scenario rows process ``process_id`` owns.

    Blocks are contiguous and cover ``total`` exactly; the last block may
    be short.  Every process must compute the SAME split (it is pure
    arithmetic on the global size).
    """
    per = -(-total // process_count)  # ceil
    start = min(process_id * per, total)
    return start, min(start + per, total)


def sweep_multihost(
    snapshot_arrays: tuple,
    cpu_reqs,
    mem_reqs,
    replicas,
    *,
    mode: str = "reference",
    gather: bool = True,
):
    """Sweep a globally-partitioned scenario grid across all hosts.

    Every process passes the FULL grid (it is tiny — three int64 vectors);
    each host computes only its :func:`scenario_block` on its local
    devices, scenario-sharded.  The snapshot arrays are replicated per
    host (node-axis sharding across hosts would put the ``psum`` on DCN —
    the wrong network for it; shard nodes only within a host via
    :func:`..parallel.sweep.sweep_shard_map`).

    With ``gather`` (default) the per-host partial results are
    all-gathered so every process returns the full ``(totals[S],
    schedulable[S])``; with ``gather=False`` each process returns only its
    own block (stitch externally, e.g. when only host 0 reports).
    """
    cpu_reqs = np.asarray(cpu_reqs, dtype=np.int64)
    mem_reqs = np.asarray(mem_reqs, dtype=np.int64)
    replicas = np.asarray(replicas, dtype=np.int64)
    s = cpu_reqs.shape[0]
    stage, width, pcount = _local_block_stager(s)
    cpu_d = stage(cpu_reqs, 1)  # pad with harmless 1-milli probes
    mem_d = stage(mem_reqs, 1)
    rep_d = stage(replicas, 0)
    arrays_d = tuple(jax.device_put(np.asarray(a)) for a in snapshot_arrays)

    totals_p, sched_p = sweep_grid(*arrays_d, cpu_d, mem_d, rep_d, mode=mode)
    return _finish(totals_p, sched_p, s, width, pcount, gather)


def _local_block_stager(s: int):
    """Shared front half of both multihost sweeps: this process's
    :func:`scenario_block` of the global grid, padded to the local device
    count and scenario-sharded over the host's chips (no cross-host
    sharding anywhere).  Returns ``(stage, width, pcount)`` where
    ``stage(a, fill)`` slices+pads+shards one grid array (1-D, or 2-D
    sharded on its scenario axis 0).
    """
    pid, pcount = jax.process_index(), jax.process_count()
    start, stop = scenario_block(s, pid, pcount)
    local_devices = jax.local_devices()
    k = max(len(local_devices), 1)
    width = stop - start
    pad = -(-max(width, 1) // k) * k - width
    mesh = Mesh(np.array(local_devices), (SCENARIO_AXIS,))
    sharding = NamedSharding(mesh, P(SCENARIO_AXIS))

    def stage(a, fill):
        block = a[start:stop]
        if pad:
            widths = ((0, pad),) + ((0, 0),) * (block.ndim - 1)
            block = np.pad(block, widths, constant_values=fill)
        return jax.device_put(block, sharding)

    return stage, width, pcount


def sweep_multihost_multi(
    alloc_rn,
    used_rn,
    alloc_pods,
    pods_count,
    healthy,
    reqs_sr,
    replicas,
    *,
    mode: str = "strict",
    gather: bool = True,
):
    """R-resource variant of :func:`sweep_multihost` (BASELINE config 4).

    Same partition scheme — every process passes the full ``[S, R]``
    request grid, owns its contiguous :func:`scenario_block`, shards it
    over local chips, and optionally all-gathers at the end.  The
    ``[R, N]`` resource matrix is replicated per host like the 2-resource
    snapshot arrays (node-axis sharding across hosts would put the
    reduction on DCN).
    """
    reqs_sr = np.asarray(reqs_sr, dtype=np.int64)
    replicas = np.asarray(replicas, dtype=np.int64)
    s = reqs_sr.shape[0]
    stage, width, pcount = _local_block_stager(s)
    # 1-probes: valid (nonzero) requests whose outputs are sliced off.
    reqs_d = stage(reqs_sr, 1)
    rep_d = stage(replicas, 0)
    node_d = tuple(
        jax.device_put(np.asarray(a))
        for a in (alloc_rn, used_rn, alloc_pods, pods_count, healthy)
    )

    totals_p, sched_p = sweep_grid_multi(*node_d, reqs_d, rep_d, mode=mode)
    return _finish(totals_p, sched_p, s, width, pcount, gather)


def _finish(totals_p, sched_p, s, width, pcount, gather):
    """Slice off probe padding, then (optionally) all-gather the blocks."""
    totals_local = np.asarray(totals_p)[:width]
    sched_local = np.asarray(sched_p)[:width]
    if not gather or pcount == 1:
        return totals_local, sched_local
    from jax.experimental import multihost_utils

    # Fixed-width blocks so the gather is a dense [pcount, per] array;
    # short tails are padded then sliced off after concatenation.
    per = -(-s // pcount)
    gathered_t = multihost_utils.process_allgather(
        np.pad(totals_local, (0, per - width))
    )
    gathered_s = multihost_utils.process_allgather(
        np.pad(sched_local, (0, per - width))
    )
    return _stitch(gathered_t, s, pcount), _stitch(gathered_s, s, pcount)


def _stitch(gathered: np.ndarray, s: int, pcount: int) -> np.ndarray:
    """``[pcount, per]`` gathered blocks → the ``[s]`` global result
    (drops each block's tail padding)."""
    blocks = []
    for p in range(pcount):
        b0, b1 = scenario_block(s, p, pcount)
        blocks.append(np.asarray(gathered[p])[: b1 - b0])
    return np.concatenate(blocks)
