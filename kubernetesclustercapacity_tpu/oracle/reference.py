"""Pure-Python oracle reproducing the reference CLI's semantics bug-for-bug.

This module re-implements the exact observable behavior of the reference's
``main`` pipeline (``src/KubeAPI/ClusterCapacity.go:48-150``) over an offline
*fixture* (a JSON-able dict of node/pod lists — the shape a Kubernetes List
response carries, minus everything the reference never reads).  It exists so
the vectorized JAX kernels have a sequential ground truth to be bit-exact
against ("bit-exact replica counts vs. the Go CPU path", BASELINE.json).

Reproduced quirks (SURVEY.md §2.4) — each is deliberate:

* Q1  conditional pod cap: applied only when ``fit >= allocatablePods``
      (``:134-136``), and it then *overwrites* the min with
      ``allocatablePods - len(pods)`` — which can be NEGATIVE.
* Q3  "healthy" = the first FOUR conditions ALL have ``Status == "False"``;
      any of them being non-``"False"`` marks the node unhealthy
      (``:212-219``) — on the legacy 5-condition layout the pressure
      conditions come first, so "no pressure reported" reads as healthy.
      Running out of conditions before j=4 (all seen being ``"False"``) is an
      index panic.
* Q4  unhealthy nodes are skipped but NOT removed: a zero-valued phantom node
      stays in the slice (``:221-226``), and its pod query matches pods with
      an empty ``nodeName`` (``:236``).  The ``make([]node, n, 3)`` crash for
      n > 3 (``:176``) is reproducible via ``emulate_slice_bug=True``.
* Q5  parse-fail→0: node memory that ``bytefmt`` rejects becomes 0
      (``:202-206``); CPU strings that ``Atoi`` rejects become 0 (``:314-317``).
* Q7  only ``Running`` pods consume capacity (field selector ``:236``); all
      namespaces; regular containers only (``:276-277``) — init containers,
      ephemeral containers and pod overhead are invisible.

Fixture schema (all quantity values are strings, as the API serves them)::

    {"nodes": [{"name": str,
                "allocatable": {"cpu": "4", "memory": "16158816Ki", "pods": "110"},
                "conditions": [{"type": str, "status": "False"|"True"|"Unknown"}, ...],
                "labels": {str: str},                  # used by constraint masks
                "taints": [{"key","value","effect"}]}, # used by constraint masks
               ...],
     "pods":  [{"name": str, "namespace": str, "nodeName": str, "phase": str,
                "containers": [{"resources": {"requests": {"cpu","memory"},
                                               "limits":   {"cpu","memory"}}}],
                "initContainers": [...],               # ignored (Q7)
                "nodeSelector": {...}, "tolerations": [...]},  # masks
               ...]}
"""

from __future__ import annotations

import functools
import math
from dataclasses import dataclass, field

from kubernetesclustercapacity_tpu.scenario import Scenario
from kubernetesclustercapacity_tpu.utils.quantity import (
    QuantityParseError,
    cpu_parse_error_payload,
    cpu_to_milli_reference,
    parse_quantity,
    to_bytes_reference,
)

__all__ = [
    "ReferencePanic",
    "NodeView",
    "PerNodeResult",
    "OracleResult",
    "healthy_nodes",
    "non_terminated_pods_for_node",
    "pod_requests_limits",
    "reference_run",
    "fit_arrays_python",
]

_UINT64_MOD = 1 << 64
_INT64_MOD = 1 << 63

# The four phases the field selector excludes (ClusterCapacity.go:236); only
# "Running" — or any novel phase string — survives it.
_EXCLUDED_PHASES = frozenset({"Pending", "Succeeded", "Failed", "Unknown"})


class ReferencePanic(RuntimeError):
    """The oracle's analog of a Go runtime panic in the reference."""


def _to_go_int(u: int) -> int:
    """Reinterpret an arbitrary Python int as a Go 64-bit signed int."""
    u %= _UINT64_MOD
    return u - _UINT64_MOD if u >= _INT64_MOD else u


def _go_div(num: int, den: int) -> int:
    """Go int64 division: truncates toward zero (Python ``//`` floors) and
    WRAPS the one overflowing quotient — ``INT64_MIN / -1 == INT64_MIN``
    in Go (two's-complement overflow is defined there)."""
    q = abs(num) // abs(den)
    q = -q if (num < 0) != (den < 0) else q
    return _to_go_int(q)


def _go_float_div(num: float, den: float) -> float:
    """Go float64 division: x/0 is ±Inf, 0/0 is NaN — never a trap."""
    if den == 0.0:
        if num == 0.0:
            return math.nan
        return math.inf if num > 0 else -math.inf
    return num / den


@dataclass
class NodeView:
    """The reference's ``type node`` (``ClusterCapacity.go:41-46``).

    A phantom (skipped-unhealthy) node is the zero value: empty name, zero
    allocatables — exactly what the reference leaves in its slice.
    """

    name: str = ""
    allocatable_cpu: int = 0  # uint64 millicores
    allocatable_memory: int = 0  # int64 bytes
    allocatable_pods: int = 0


@dataclass
class PerNodeResult:
    """Everything the reference prints/accumulates per node (``:105-140``)."""

    node: NodeView
    pods_count: int
    cpu_limits_milli: int
    cpu_requests_milli: int
    mem_limits_bytes: int
    mem_requests_bytes: int
    cpu_request_used_percent: float
    mem_request_used_percent: float
    cpu_limit_used_percent: float
    mem_limit_used_percent: float
    max_replicas: int


@dataclass
class OracleResult:
    """Aggregate outcome of one reference-semantics run."""

    per_node: list[PerNodeResult] = field(default_factory=list)
    total_possible_replicas: int = 0
    replicas_requested: int = 0

    @property
    def schedulable(self) -> bool:
        # ClusterCapacity.go:144
        return self.total_possible_replicas >= self.replicas_requested

    @property
    def fits(self) -> list[int]:
        return [r.max_replicas for r in self.per_node]


def healthy_nodes(
    fixture: dict, *, emulate_slice_bug: bool = False
) -> list[NodeView]:
    """Replicates ``getHealthyNodes`` (``ClusterCapacity.go:166-230``).

    * allocatable CPU via the reference CPU codec (``:196-197``);
    * allocatable memory via ``bytefmt`` with error→0 (``:199-206``);
    * allocatable pods via the real Quantity grammar (``.Pods().Value()``,
      ``:208``);
    * health: the first four conditions must all be ``"False"`` — any other
      status marks the node unhealthy (``:212-219``); running out of
      conditions before the fourth is an index-out-of-range panic;
    * unhealthy nodes leave a zero-valued phantom entry (``:221-226``).

    With ``emulate_slice_bug=True``, reproduces the ``make([]node, n, 3)``
    len>cap crash for clusters of more than 3 nodes (``:176``) — the default
    diverges and succeeds (SURVEY.md §2.4 Q4).
    """
    raw_nodes = fixture.get("nodes", [])
    if emulate_slice_bug and len(raw_nodes) > 3:
        raise ReferencePanic(
            f"makeslice: len out of range (len {len(raw_nodes)} > cap 3, "
            "ClusterCapacity.go:176)"
        )

    result = [NodeView() for _ in raw_nodes]
    for i, raw in enumerate(raw_nodes):
        allocatable = raw.get("allocatable", {})
        cpu_milli, mem_bytes, alloc_pods, _ = node_allocatable_values(
            allocatable.get("cpu", "0"),
            allocatable.get("memory", ""),
            allocatable.get("pods", "0"),
        )
        if node_is_healthy_reference(raw):
            result[i] = NodeView(
                name=raw.get("name", ""),
                allocatable_cpu=cpu_milli,
                allocatable_memory=mem_bytes,
                allocatable_pods=alloc_pods,
            )
    return result


def node_allocatable_values(
    cpu_str, mem_str, pods_str
) -> tuple[int, int, int, str | None]:
    """One node's allocatable parses with ``getHealthyNodes``' exact error
    semantics: CPU codec errors raise through (``:196-197``), memory
    parse failure is a silent zero (``:202-206``), pods parse failure is
    zero (``.Pods().Value()`` of a missing/invalid quantity, ``:208``).
    The fourth element is the CPU codec's error-line payload (the
    suffix-stripped string ``convertCPUToMilis`` prints, ``:314-317``)
    or ``None`` — transcript parity replays it.  Single-sourced here so
    the columnar packer (``snapshot.py``) and the per-node walk above
    cannot drift.
    """
    cpu_milli = cpu_to_milli_reference(cpu_str)
    try:
        mem_bytes = to_bytes_reference(mem_str)
    except QuantityParseError:
        mem_bytes = 0  # :202-206 — silent zero
    try:
        alloc_pods = parse_quantity(pods_str).value()
    except QuantityParseError:
        alloc_pods = 0
    return cpu_milli, mem_bytes, alloc_pods, cpu_parse_error_payload(cpu_str)


def node_is_healthy_reference(raw: dict) -> bool:
    """The first-four-conditions health check, bug-for-bug (``:212-219``):
    any of the first 4 conditions not ``"False"`` → unhealthy; fewer than
    4 conditions → the reference's index-out-of-range panic."""
    conditions = raw.get("conditions", [])
    for j in range(4):  # :212 — hardcoded first four
        if j >= len(conditions):
            raise ReferencePanic(
                f"index out of range [{j}] with length {len(conditions)} "
                f"(node {raw.get('name', '?')!r}, ClusterCapacity.go:213)"
            )
        if conditions[j].get("status") != "False":
            return False
    return True


def _survives_field_selector(pod: dict) -> bool:
    """The phase half of the field selector (``ClusterCapacity.go:236``)."""
    return pod.get("phase") not in _EXCLUDED_PHASES


def non_terminated_pods_for_node(fixture: dict, node_name: str) -> list[dict]:
    """Replicates the field-selector pod list (``ClusterCapacity.go:232-253``).

    Matches pods whose ``spec.nodeName`` equals ``node_name`` and whose phase
    is none of Pending/Succeeded/Failed/Unknown, across ALL namespaces.  For a
    phantom node (``node_name == ""``) this matches unscheduled pods — the
    selector degenerates to ``spec.nodeName=`` (Q4).
    """
    return [
        p
        for p in fixture.get("pods", [])
        if p.get("nodeName", "") == node_name and _survives_field_selector(p)
    ]


def pods_by_node_index(fixture: dict) -> dict[str, list[dict]]:
    """Group field-selector-surviving pods by nodeName in one pass.

    Per-node list order matches :func:`non_terminated_pods_for_node` (both
    preserve fixture order), so sums computed either way are identical — this
    just avoids the reference's per-node rescan (a fresh apiserver List per
    node at ``:238``).
    """
    index: dict[str, list[dict]] = {}
    for p in fixture.get("pods", []):
        if _survives_field_selector(p):
            index.setdefault(p.get("nodeName", ""), []).append(p)
    return index


def pod_requests_limits(pods: list[dict]) -> tuple[int, int, int, int]:
    """Replicates ``getPodCPUMemoryRequestsLimits`` (``ClusterCapacity.go:255-299``).

    Sums over regular containers only.  CPU strings go through the reference
    codec (an absent resource is the zero Quantity whose ``String()`` is
    ``"0"`` → 0); memory uses the real Quantity grammar (``Memory().Value()``,
    ``:285-286``) with absent → 0.  Returns
    ``(cpu_limits, cpu_requests, mem_limits, mem_requests)`` in the
    reference's order, with Go integer wrapping on the running sums.
    """
    cpu_req_total = cpu_lim_total = 0  # uint64 in Go
    mem_req_total = mem_lim_total = 0  # int64 in Go
    for pod in pods:
        for container in pod.get("containers", []):
            resources = container.get("resources", {})
            limits = resources.get("limits", {})
            requests = resources.get("requests", {})
            cpu_lim_total = (
                cpu_lim_total + cpu_to_milli_reference(limits.get("cpu", "0"))
            ) % _UINT64_MOD
            cpu_req_total = (
                cpu_req_total + cpu_to_milli_reference(requests.get("cpu", "0"))
            ) % _UINT64_MOD
            mem_lim_total = _to_go_int(
                mem_lim_total + _mem_value(limits.get("memory"))
            )
            mem_req_total = _to_go_int(
                mem_req_total + _mem_value(requests.get("memory"))
            )
    return cpu_lim_total, cpu_req_total, mem_lim_total, mem_req_total


@functools.lru_cache(maxsize=1 << 16)
def _mem_value(s: str | None) -> int:
    """``Quantity.Value()`` of a container memory string; absent/invalid → 0.

    (An invalid quantity cannot exist in a real API object — the apiserver
    validates — so zero matches what the zero Quantity would report.)
    Memoized: pod memory strings repeat across a cluster (see
    ``utils.quantity``'s cache note).
    """
    if s is None:
        return 0
    try:
        return parse_quantity(s).value()
    except QuantityParseError:
        return 0


def fit_arrays_python(
    alloc_cpu,
    alloc_mem,
    alloc_pods,
    used_cpu,
    used_mem,
    pods_count,
    cpu_req: int,
    mem_req: int,
    *,
    mode: str = "reference",
    healthy=None,
) -> list[int]:
    """Sequential fit over raw int64 arrays — the array-level ground truth.

    ``mode="reference"`` is the same arithmetic as :func:`reference_run`'s
    per-node loop (bit patterns: CPU values are uint64 reinterpreted, zero
    requests panic at division exactly where Go would); ``mode="strict"``
    mirrors the kernel's corrected semantics (3-way min with remaining pod
    slots, clamped at 0, unhealthy nodes contribute nothing — ``healthy``
    defaults to all-healthy).  Lets parity tests and the CPU CLI backend feed
    this scalar loop and the JAX kernel identical arrays in either mode.
    """
    if mode not in ("reference", "strict"):
        raise ValueError(f"unknown mode {mode!r}")
    fits = []
    cr = int(cpu_req) % _UINT64_MOD
    mr = int(mem_req)
    for i in range(len(alloc_cpu)):
        ac = int(alloc_cpu[i]) % _UINT64_MOD  # uint64 view of the bit pattern
        uc = int(used_cpu[i]) % _UINT64_MOD
        if ac <= uc:
            cpu_fit = 0
        else:
            if cr == 0:
                raise ReferencePanic(
                    "integer divide by zero (ClusterCapacity.go:123)"
                )
            cpu_fit = _to_go_int((ac - uc) // cr)
        am, um = int(alloc_mem[i]), int(used_mem[i])
        if am <= um:
            mem_fit = 0
        else:
            if mr == 0:
                raise ReferencePanic(
                    "integer divide by zero (ClusterCapacity.go:129)"
                )
            mem_fit = _go_div(_to_go_int(am - um), mr)
        fit = cpu_fit if cpu_fit <= mem_fit else mem_fit
        ap = int(alloc_pods[i])
        if mode == "reference":
            if fit >= ap:
                fit = ap - int(pods_count[i])
        else:
            slots = max(ap - int(pods_count[i]), 0)
            fit = max(min(fit, slots), 0)
            if healthy is not None and not bool(healthy[i]):
                fit = 0
        fits.append(fit)
    return fits


def reference_run(
    fixture: dict,
    scenario: Scenario,
    *,
    emulate_slice_bug: bool = False,
) -> OracleResult:
    """Full bug-for-bug run of the reference ``main`` over a fixture.

    The per-node loop (``ClusterCapacity.go:105-140``)::

        cpuFit = 0 if allocCPU <= usedCPUreq else (allocCPU - usedCPUreq) / cpuReq
        memFit = 0 if allocMem <= usedMemReq else (allocMem - usedMemReq) / memReq
        fit    = min(cpuFit, memFit)
        if fit >= allocatablePods: fit = allocatablePods - len(pods)   # Q1
        total += fit

    Integer division floors (all operands non-negative after the guards);
    ``cpuReq == 0`` panics exactly where the reference does (``:123``).
    """
    nodes = healthy_nodes(fixture, emulate_slice_bug=emulate_slice_bug)
    result = OracleResult(replicas_requested=scenario.replicas)

    pods_by_node = pods_by_node_index(fixture)

    for node in nodes:
        pods = pods_by_node.get(node.name, [])
        cpu_lim, cpu_req_used, mem_lim, mem_req_used = pod_requests_limits(pods)

        per = PerNodeResult(
            node=node,
            pods_count=len(pods),
            cpu_limits_milli=cpu_lim,
            cpu_requests_milli=cpu_req_used,
            mem_limits_bytes=mem_lim,
            mem_requests_bytes=mem_req_used,
            cpu_request_used_percent=_go_float_div(
                float(cpu_req_used) * 100, float(node.allocatable_cpu)
            ),
            mem_request_used_percent=_go_float_div(
                float(mem_req_used) * 100, float(node.allocatable_memory)
            ),
            cpu_limit_used_percent=_go_float_div(
                float(cpu_lim) * 100, float(node.allocatable_cpu)
            ),
            mem_limit_used_percent=_go_float_div(
                float(mem_lim) * 100, float(node.allocatable_memory)
            ),
            max_replicas=0,
        )

        if node.allocatable_cpu <= cpu_req_used:
            cpu_fit = 0  # :119-121
        else:
            if scenario.cpu_request_milli == 0:
                raise ReferencePanic(
                    "integer divide by zero (ClusterCapacity.go:123)"
                )
            cpu_fit = _to_go_int(
                (node.allocatable_cpu - cpu_req_used) // scenario.cpu_request_milli
            )

        if node.allocatable_memory <= mem_req_used:
            mem_fit = 0  # :125-127
        else:
            if scenario.mem_request_bytes == 0:
                raise ReferencePanic(
                    "integer divide by zero (ClusterCapacity.go:129)"
                )
            # int64 subtraction wraps (mem_req_used can be negative after a
            # wrapped sum, making the exact difference exceed int64), and Go
            # division truncates toward zero.
            mem_fit = _go_div(
                _to_go_int(node.allocatable_memory - mem_req_used),
                scenario.mem_request_bytes,
            )

        max_replicas = cpu_fit if cpu_fit <= mem_fit else mem_fit  # findMin :159-164
        if max_replicas >= node.allocatable_pods:  # Q1, :134-136
            max_replicas = node.allocatable_pods - len(pods)

        per.max_replicas = max_replicas
        result.per_node.append(per)
        result.total_possible_replicas += max_replicas

    return result
