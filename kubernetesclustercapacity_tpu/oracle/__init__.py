"""Bug-for-bug reference-semantics oracle — the bit-exactness gate.

The Go toolchain is absent in this environment (SURVEY.md §4), so this pure
Python walk of the reference's exact control flow stands in for
``go run ClusterCapacity.go`` when validating the JAX/TPU kernels.
"""

from kubernetesclustercapacity_tpu.oracle.reference import (  # noqa: F401
    NodeView,
    OracleResult,
    PerNodeResult,
    ReferencePanic,
    fit_arrays_python,
    healthy_nodes,
    non_terminated_pods_for_node,
    pod_requests_limits,
    reference_run,
)
