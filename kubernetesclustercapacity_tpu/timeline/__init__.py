"""Capacity timeline: per-generation history, drift attribution, alerting.

The service layers before this one observe the *service* (request
counters, traces, the flight recorder); nothing observes the *domain*.
Snapshot generations arrive through the coalescer, capacity silently
jumps, and nobody can say which nodes or which binding constraint moved
it — exactly the drift problem that motivates chance-constrained
capacity planning (arXiv:2207.11122, arXiv:2511.08373): the question an
operator asks a live `-follow` server is never "how many replicas fit
right now" but "what changed, when, and why did my headroom move".

Four pieces, each independently usable:

* :mod:`.watchlist` — named what-if scenarios (``-watch FILE``,
  YAML/JSON) the timeline re-evaluates on every snapshot publish, each
  with an optional ``min_replicas`` alert threshold;
* :mod:`.diff`      — the generation-to-generation node-set diff engine
  (added/removed/mutated nodes with per-resource allocatable deltas),
  invertible by construction: ``apply(old, diff) == new`` is a pinned
  property, so a recorded diff IS the generation transition;
* :mod:`.alerts`    — the per-watch ok → breached → recovered state
  machine behind the ``kccap_watch_*`` gauges, ``/healthz`` and doctor;
* :mod:`.history`   — :class:`~.history.CapacityTimeline`, the bounded
  thread-safe ring of :class:`~.history.GenerationRecord` entries the
  server feeds from the coalescer publish thread (off the request path,
  riding the same warm pre-stage the device cache uses), and the delta
  attribution that joins the diff with the explain pass's binding
  histograms ("capacity 41→37: node pool-b-7 drained, binding
  constraint shifted memory→pods on 12 nodes").

Watch capacities are evaluated through :func:`~..explain.explain_snapshot`,
whose fit column is pinned bit-identical to :func:`~..ops.fit.fit_per_node`
— so a timeline entry's capacity equals a cold ``fit`` of the same
generation by construction, in both semantics modes.
"""

from kubernetesclustercapacity_tpu.timeline.alerts import (  # noqa: F401
    ALERT_BREACHED,
    ALERT_OK,
    ALERT_RECOVERED,
    WatchAlert,
)
from kubernetesclustercapacity_tpu.timeline.diff import (  # noqa: F401
    NODE_FIELDS,
    SnapshotDiff,
    diff_summaries,
    node_summary,
    snapshot_digest,
)
from kubernetesclustercapacity_tpu.timeline.history import (  # noqa: F401
    CapacityTimeline,
    GenerationRecord,
)
from kubernetesclustercapacity_tpu.timeline.watchlist import (  # noqa: F401
    WatchError,
    WatchSpec,
    load_watchlist,
)
