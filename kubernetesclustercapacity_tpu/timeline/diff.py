"""Generation-to-generation snapshot diffing: the drift engine.

A :class:`~..snapshot.ClusterSnapshot` is summarized into a per-node
mapping (:func:`node_summary`) and two summaries diff into a
:class:`SnapshotDiff` — nodes added, nodes removed, and nodes mutated
with per-resource deltas.  The diff is *invertible by construction*:
``diff_summaries(old, new).apply(old) == new`` is a pinned property
(``tests/test_timeline.py``), so a recorded diff is a faithful record of
the generation transition, not a lossy rendering of it.

Node identity is the node NAME, which Kubernetes guarantees unique —
except for the reference packer's phantom rows, which all share ``""``
(and fixtures can carry duplicates).  Repeated names are disambiguated
positionally (``name#1``, ``name#2`` …) so every row keeps a stable key
and a churned duplicate shows up as a mutation/removal rather than
silently aliasing its namesake.

All arithmetic is Python-int (the summaries hold plain ints), so wrapped
uint64 CPU carriers survive the round trip bit-exactly.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

import numpy as np

from kubernetesclustercapacity_tpu.snapshot import ClusterSnapshot

__all__ = [
    "NODE_FIELDS",
    "SnapshotDiff",
    "diff_summaries",
    "node_summary",
    "shape_key",
    "snapshot_digest",
]

#: The per-node columns a summary row carries, in tuple order.  These are
#: exactly the arrays the fit kernels consume (plus health), so a zero
#: diff proves the two generations answer every query identically.
NODE_FIELDS = (
    "alloc_cpu_milli",
    "alloc_mem_bytes",
    "alloc_pods",
    "used_cpu_req_milli",
    "used_mem_req_bytes",
    "pods_count",
    "healthy",
)

_DIGEST_HEX = 16  # matches the flight recorder's truncation


def node_summary(snap: ClusterSnapshot) -> dict[str, tuple[int, ...]]:
    """``{node key: per-field int tuple}`` in snapshot row order.

    Keys are node names; a repeated name gets ``#<occurrence>`` appended
    from its second occurrence on, so phantom ``""`` rows and duplicate
    fixtures keep one key per ROW.  ``healthy`` rides as 0/1.
    """
    cols = [
        np.asarray(getattr(snap, f)).astype(np.int64) for f in NODE_FIELDS
    ]
    out: dict[str, tuple[int, ...]] = {}
    seen: dict[str, int] = {}
    for i, name in enumerate(snap.names):
        n = seen.get(name, 0)
        seen[name] = n + 1
        key = name if n == 0 else f"{name}#{n}"
        out[key] = tuple(int(c[i]) for c in cols)
    return out


def snapshot_digest(snap: ClusterSnapshot) -> str:
    """Truncated SHA-256 over the summary columns + names: two snapshots
    share a digest iff every fit-relevant column matches row for row
    (same truncation as the flight recorder's request digests)."""
    h = hashlib.sha256()
    h.update("\x00".join(snap.names).encode())
    h.update(snap.semantics.encode())
    for f in NODE_FIELDS:
        arr = np.ascontiguousarray(np.asarray(getattr(snap, f)).astype(np.int64))
        h.update(arr.tobytes())
    return h.hexdigest()[:_DIGEST_HEX]


def shape_key(row: tuple[int, ...]) -> str:
    """Stable short identifier of a node SHAPE (a summary row's field
    tuple): two rows share a key iff every fit-relevant column matches —
    the same equivalence the grouped snapshot compresses on
    (:meth:`..snapshot.ClusterSnapshot.grouped`), so drift attribution
    can say *which* group a churned node joined or left."""
    h = hashlib.sha256("|".join(str(int(v)) for v in row).encode())
    return h.hexdigest()[:8]


@dataclass
class SnapshotDiff:
    """One generation transition: added/removed rows and per-field deltas.

    ``added``/``removed`` carry the full field tuple (``removed`` holds
    the OLD values, making the diff invertible); ``changed`` maps node
    key → ``{field: new - old}`` with zero-delta fields omitted.
    """

    added: dict[str, tuple[int, ...]] = field(default_factory=dict)
    removed: dict[str, tuple[int, ...]] = field(default_factory=dict)
    changed: dict[str, dict[str, int]] = field(default_factory=dict)

    @property
    def empty(self) -> bool:
        return not (self.added or self.removed or self.changed)

    def apply(self, old: dict[str, tuple[int, ...]]) -> dict[str, tuple[int, ...]]:
        """``old ⊕ diff``: reconstruct the new summary (the round-trip
        contract ``diff_summaries(a, b).apply(a) == b``)."""
        out: dict[str, tuple[int, ...]] = {}
        for key, row in old.items():
            if key in self.removed:
                continue
            deltas = self.changed.get(key)
            if deltas:
                out[key] = tuple(
                    v + deltas.get(f, 0) for f, v in zip(NODE_FIELDS, row)
                )
            else:
                out[key] = row
        out.update(self.added)
        return out

    def to_wire(self) -> dict:
        """JSON-able shape for the ``timeline`` op: keys + per-field
        deltas (full tuples for added/removed are summarized as dicts so
        the wire stays self-describing)."""
        return {
            "nodes_added": [
                {"node": k, **dict(zip(NODE_FIELDS, v))}
                for k, v in self.added.items()
            ],
            "nodes_removed": [
                {"node": k, **dict(zip(NODE_FIELDS, v))}
                for k, v in self.removed.items()
            ],
            "nodes_changed": [
                {"node": k, "deltas": dict(d)}
                for k, d in self.changed.items()
            ],
        }


def diff_summaries(
    old: dict[str, tuple[int, ...]], new: dict[str, tuple[int, ...]]
) -> SnapshotDiff:
    """Diff two :func:`node_summary` mappings (pure dict/int math)."""
    diff = SnapshotDiff()
    for key, row in new.items():
        prev = old.get(key)
        if prev is None:
            diff.added[key] = row
        elif prev != row:
            diff.changed[key] = {
                f: b - a
                for f, a, b in zip(NODE_FIELDS, prev, row)
                if b != a
            }
    for key, row in old.items():
        if key not in new:
            diff.removed[key] = row
    return diff
