"""Per-watch alert state machine: ok → breached → recovered.

A watch with a ``min_replicas`` threshold transitions when its evaluated
capacity crosses it:

* ``ok``        — never breached since the timeline started;
* ``breached``  — current capacity < ``min_replicas``;
* ``recovered`` — capacity back at/above the threshold after at least
  one breach (distinguishable from ``ok`` on purpose: "fine now, but it
  dipped while you were asleep" is the whole point of a timeline).

Transitions are *returned* to the caller (the timeline appends them to
the ``-timeline-log`` JSONL and bumps the breach counters) rather than
observed via callbacks — the machine itself is pure state, trivially
testable, and takes no locks (the timeline serializes observations).
"""

from __future__ import annotations

from dataclasses import dataclass

__all__ = [
    "ALERT_OK",
    "ALERT_BREACHED",
    "ALERT_RECOVERED",
    "ALERT_STATE_CODES",
    "WatchAlert",
]

ALERT_OK = "ok"
ALERT_BREACHED = "breached"
ALERT_RECOVERED = "recovered"

#: Gauge encoding (``kccap_watch_alert_state``): 0 is the healthy floor
#: so any nonzero sample means "look at this watch".
ALERT_STATE_CODES = {ALERT_OK: 0, ALERT_RECOVERED: 1, ALERT_BREACHED: 2}


@dataclass
class WatchAlert:
    """Alert state for one watch (``min_replicas`` may be ``None`` —
    such a watch is observed but never transitions)."""

    name: str
    min_replicas: int | None = None
    state: str = ALERT_OK
    breaches: int = 0
    recoveries: int = 0
    last_total: int | None = None
    since_generation: int | None = None  # generation of the last transition

    def update(self, total: int, generation: int) -> str | None:
        """Fold one evaluated capacity in; returns the transition entered
        (``"breached"`` / ``"recovered"``) or ``None`` when state held."""
        self.last_total = int(total)
        if self.min_replicas is None:
            return None
        breached_now = total < self.min_replicas
        if breached_now and self.state != ALERT_BREACHED:
            self.state = ALERT_BREACHED
            self.breaches += 1
            self.since_generation = generation
            return ALERT_BREACHED
        if not breached_now and self.state == ALERT_BREACHED:
            self.state = ALERT_RECOVERED
            self.recoveries += 1
            self.since_generation = generation
            return ALERT_RECOVERED
        return None

    @property
    def state_code(self) -> int:
        return ALERT_STATE_CODES[self.state]

    def to_wire(self) -> dict:
        """JSON-able state (``timeline`` op, ``/healthz``, doctor)."""
        return {
            "state": self.state,
            "min_replicas": self.min_replicas,
            "breaches": self.breaches,
            "recoveries": self.recoveries,
            "last_total": self.last_total,
            "since_generation": self.since_generation,
        }
