"""The capacity timeline: a bounded ring of per-generation records.

:class:`CapacityTimeline` is fed one call per snapshot publish —
``observe(snapshot, generation)`` — by the server's swap paths, which
for a live ``-follow`` deployment means the COALESCER'S worker thread
(the same off-request-path thread that pre-warms the device cache, so a
watchlist evaluation rides a warm cache and never adds latency to a
dispatched query).  Each observation captures:

* the snapshot digest and per-node summary (:mod:`.diff`'s vocabulary);
* the evaluated capacity of every watchlist scenario, through
  :func:`~..explain.explain_snapshot` — whose fit column is pinned
  bit-identical to :func:`~..ops.fit.fit_per_node`, so a timeline
  capacity IS a cold ``fit`` of that generation — plus the binding
  histogram the drift attribution consumes;
* alert transitions (:mod:`.alerts`), appended to the ``-timeline-log``
  JSONL alongside one line per generation.

``deltas()`` joins consecutive records into attributed transitions: the
node-set diff, per-watch capacity movement, the binding-constraint shift
(:func:`~..explain.binding_shift`), and the per-node fit contributions
that say WHICH nodes moved the total.

Telemetry honors the process switch exactly like every other layer:
with ``KCCAP_TELEMETRY=0`` (or no registry) an observation makes zero
registry calls.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from kubernetesclustercapacity_tpu.explain import (
    binding_shift,
    explain_snapshot,
)
from kubernetesclustercapacity_tpu.masks import implicit_taint_mask
from kubernetesclustercapacity_tpu.scenario import ScenarioGrid
from kubernetesclustercapacity_tpu.snapshot import ClusterSnapshot
from kubernetesclustercapacity_tpu.telemetry.metrics import (
    enabled as _telemetry_enabled,
)
from kubernetesclustercapacity_tpu.timeline.alerts import WatchAlert
from kubernetesclustercapacity_tpu.timeline.diff import (
    diff_summaries,
    node_summary,
    shape_key,
    snapshot_digest,
)
from kubernetesclustercapacity_tpu.timeline.watchlist import WatchSpec

__all__ = ["CapacityTimeline", "GenerationRecord", "WatchResult"]

#: Per-watch node contributions reported per delta (the full diff rides
#: alongside; the contributor list is the "which nodes moved it" headline
#: and stays readable at 10k-node scale).
_MAX_CONTRIBUTORS = 8


def _shift_phrase(shift: dict[str, int]) -> str:
    """Human rendering of a binding shift.  The common drift — nodes
    moving from one binding constraint to exactly one other — reads as
    ``memory→pods on 12 nodes``; anything messier falls back to signed
    per-constraint counts."""
    losers = {k: -v for k, v in shift.items() if v < 0}
    gainers = {k: v for k, v in shift.items() if v > 0}
    if len(losers) == 1 and len(gainers) == 1:
        (src, n_src), (dst, n_dst) = losers.popitem(), gainers.popitem()
        if n_src == n_dst:
            return f"binding constraint shifted {src}→{dst} on {n_src} node(s)"
    parts = ", ".join(f"{k}{v:+d}" for k, v in sorted(shift.items()))
    return f"binding counts moved: {parts}"


def _delta_summary(
    name: str, before: int, after: int, diff, shift, contributions,
    shape_joins: dict[str, str] | None = None,
) -> str:
    """The one-line attribution an operator reads first, e.g.
    ``capacity 41→37: node pool-b-7 removed (-4); binding constraint
    shifted memory→pods on 12 node(s)``.

    ``shape_joins`` maps added node keys to the :func:`..diff.shape_key`
    of an EXISTING shape group they joined — those render as
    ``(+1 shape <key>)`` drift lines even when the node's capacity
    contribution is zero, so a replica landing in an existing group is
    never a silent no-op.
    """
    head = f"{name}: capacity {before}→{after}"
    if before == after and diff.empty:
        return head + " (no change)"
    shape_joins = shape_joins or {}
    clauses: list[str] = []
    seen_added: set[str] = set()
    kind_verb = {"added": "added", "removed": "removed", "mutated": "changed"}
    for key, c, kind in contributions[:3]:
        sk = shape_joins.get(key) if kind == "added" else None
        if sk is not None:
            seen_added.add(key)
            clauses.append(
                f"node {key or '<phantom>'} added ({c:+d}, +1 shape {sk})"
            )
        else:
            clauses.append(
                f"node {key or '<phantom>'} {kind_verb[kind]} ({c:+d})"
            )
    extra = len(contributions) - 3
    if extra > 0:
        clauses.append(f"{extra} more node(s)")
    # Shape joins whose capacity contribution was zero still drift the
    # group census — name them (bounded, like the contributor list).
    silent = [k for k in shape_joins if k not in seen_added][:3]
    for key in silent:
        clauses.append(
            f"node {key or '<phantom>'} added (+1 shape {shape_joins[key]})"
        )
    if shift:
        clauses.append(_shift_phrase(shift))
    if not clauses:
        clauses.append(
            f"{len(diff.added)} node(s) added, "
            f"{len(diff.removed)} removed, {len(diff.changed)} changed"
        )
    return head + ": " + "; ".join(clauses)


@dataclass
class WatchResult:
    """One watch evaluated against one generation.

    For a capacity-at-risk watch (``quantile`` set) ``total`` is the
    Monte Carlo capacity quantile — the fit of the quantile-realizing
    usage sample, so ``fits``/``binding_counts`` stay node-granular and
    the delta attribution works unchanged; ``prob_fit`` is the fraction
    of samples that fit the spec's replicas.
    """

    name: str
    mode: str
    total: int
    schedulable: bool
    breached: bool
    min_replicas: int | None
    binding_counts: dict[str, int]
    fits: np.ndarray  # [N] per-node, aligned with the record's node keys
    quantile: float | None = None
    prob_fit: float | None = None
    samples: int = 0
    car_eval_ms: float = 0.0
    #: Gang watch fields (``gang_ranks > 0`` marks one): ``total`` is
    #: then WHOLE GANGS, ``gang_binding`` the binding topology level.
    gang_ranks: int = 0
    gang_count: int = 0
    gang_binding: str | None = None
    gang_summary: str = ""
    #: Forecast watch fields (``horizon_s`` non-None marks one):
    #: ``total`` stays the NOW (h=0) quantile capacity, while
    #: ``horizon_min_capacity`` is the minimum projected capacity
    #: across the horizon (what the alert machine thresholds) and
    #: ``time_to_breach_s`` the projected seconds until the quantile
    #: first crosses the threshold — ``None`` when the trend is flat
    #: or the ring's history is insufficient to fit one.
    horizon_s: float | None = None
    time_to_breach_s: float | None = None
    horizon_min_capacity: int | None = None
    degraded_time_axis: bool = False

    def to_wire(self) -> dict:
        out = {
            "total": self.total,
            "schedulable": self.schedulable,
            "breached": self.breached,
            "mode": self.mode,
            "min_replicas": self.min_replicas,
            "binding_counts": dict(self.binding_counts),
        }
        if self.quantile is not None:
            out["quantile"] = self.quantile
            out["prob_fit"] = self.prob_fit
            out["samples"] = self.samples
        if self.gang_ranks:
            out["gang"] = {
                "ranks": self.gang_ranks,
                "count": self.gang_count,
                "binding": self.gang_binding,
                "summary": self.gang_summary,
            }
        if self.horizon_s is not None:
            out["horizon_s"] = self.horizon_s
            out["time_to_breach_s"] = self.time_to_breach_s
            out["horizon_min_capacity"] = self.horizon_min_capacity
            out["degraded_time_axis"] = self.degraded_time_axis
        return out


@dataclass
class GenerationRecord:
    """Everything the timeline remembers about one published generation."""

    generation: int
    ts: float
    digest: str
    semantics: str
    n_nodes: int
    healthy_nodes: int
    summary: dict[str, tuple[int, ...]]
    watches: dict[str, WatchResult] = field(default_factory=dict)
    eval_ms: float = 0.0

    @property
    def keys(self) -> list[str]:
        """Node keys in snapshot row order (summary insertion order)."""
        return list(self.summary)

    def to_wire(self, watch: str | None = None) -> dict:
        """JSON-able record (no per-node payloads — those feed ``deltas``)."""
        return {
            "generation": self.generation,
            "ts": self.ts,
            "digest": self.digest,
            "semantics": self.semantics,
            "nodes": self.n_nodes,
            "healthy_nodes": self.healthy_nodes,
            "eval_ms": round(self.eval_ms, 3),
            "watches": {
                name: r.to_wire()
                for name, r in self.watches.items()
                if watch is None or name == watch
            },
        }


class CapacityTimeline:
    """Thread-safe bounded capacity history + watchlist alerting.

    ``observe`` is serialized by an internal lock (snapshot publishes are
    already serialized upstream; the lock makes direct embedding safe
    too) and never raises into its caller's publish path by CONTRACT of
    the caller — the server wraps it best-effort, same as every other
    observability hook.

    ``registry`` wires the ``kccap_generation`` / ``kccap_watch_*``
    metric families; ``None`` (or ``KCCAP_TELEMETRY=0`` at construction)
    keeps the timeline registry-silent.  ``log`` is an optional JSONL
    appender — a path or a :class:`~..telemetry.tracing.TraceLog` — that
    receives one line per observed generation and one per alert
    transition (the flight-recorder-style durable record).
    """

    def __init__(
        self,
        watches: tuple[WatchSpec, ...] = (),
        *,
        depth: int = 64,
        registry=None,
        log=None,
    ) -> None:
        from kubernetesclustercapacity_tpu.telemetry.tracing import TraceLog

        if depth < 2:
            # One record cannot diff against anything; the whole point
            # of a timeline is the transition.
            raise ValueError(f"timeline depth must be >= 2, got {depth}")
        names = [w.name for w in watches]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate watch names: {names}")
        self.watches: tuple[WatchSpec, ...] = tuple(watches)
        self.depth = int(depth)
        self._lock = threading.Lock()
        self._ring: deque[GenerationRecord] = deque(maxlen=self.depth)
        self._alerts = {
            w.name: WatchAlert(w.name, w.min_replicas) for w in self.watches
        }
        #: Names of the forecast (horizon) watches — quantile watches
        #: that project forward; they report under the
        #: ``kccap_forecast_*`` family, NOT the CaR one (each watch
        #: belongs to exactly one alert funnel).
        self._horizon_names = frozenset(
            w.name for w in self.watches if w.horizon_steps is not None
        )
        #: Names of the capacity-at-risk (quantile) watches — the slice
        #: whose breaches additionally flip ``/healthz`` and the
        #: ``kccap_car_*`` gauges.
        self._car_names = (
            frozenset(
                w.name for w in self.watches if w.quantile is not None
            )
            - self._horizon_names
        )
        #: Names of the gang watches — the slice whose breaches (like
        #: the CaR slice's) flip ``/healthz`` and the ``kccap_gang_*``
        #: gauges: a breached gang watch says "fewer than N whole
        #: gangs fit", which a training-job admission plane must see.
        self._gang_names = frozenset(
            w.name for w in self.watches if w.gang is not None
        )
        self._log = TraceLog(log) if isinstance(log, str) else log
        self._m = None
        if registry is not None and _telemetry_enabled():
            self._m = {
                "generation": registry.gauge(
                    "kccap_generation",
                    "Served snapshot generation last observed.",
                ),
                "records": registry.gauge(
                    "kccap_timeline_records",
                    "Generation records currently held in the timeline.",
                ),
                "replicas": registry.gauge(
                    "kccap_watch_replicas",
                    "Evaluated capacity of a watchlist scenario.",
                    ("watch",),
                ),
                "headroom": registry.gauge(
                    "kccap_watch_headroom_pct",
                    "Capacity headroom above the watch threshold "
                    "(min_replicas, else the spec's replicas), percent.",
                    ("watch",),
                ),
                "alert_state": registry.gauge(
                    "kccap_watch_alert_state",
                    "Watch alert state (0=ok, 1=recovered, 2=breached).",
                    ("watch",),
                ),
                "breaches": registry.counter(
                    "kccap_watch_breaches_total",
                    "min_replicas breaches entered, by watch.",
                    ("watch",),
                ),
                "changes": registry.counter(
                    "kccap_watch_capacity_changes_total",
                    "Generation-to-generation capacity moves, by watch "
                    "and direction.",
                    ("watch", "direction"),
                ),
                "eval": registry.histogram(
                    "kccap_timeline_eval_seconds",
                    "Wall time of one whole-watchlist evaluation "
                    "(coalescer thread, off the request path).",
                ),
            }
            if self._gang_names:
                # The gang family, registered only when a gang watch
                # exists (same shape policy as the CaR family below).
                self._m.update(
                    {
                        "gang_capacity": registry.gauge(
                            "kccap_gang_capacity",
                            "Whole gangs of the watch's gang spec "
                            "that currently fit.",
                            ("watch",),
                        ),
                        "gang_alert_state": registry.gauge(
                            "kccap_gang_alert_state",
                            "Gang watch alert state "
                            "(0=ok, 1=recovered, 2=breached).",
                            ("watch",),
                        ),
                    }
                )
            if self._car_names:
                # The capacity-at-risk family, registered only when a
                # quantile watch exists (a plain timeline's registry
                # shape stays byte-identical to the pre-CaR one).
                self._m.update(
                    {
                        "car_replicas": registry.gauge(
                            "kccap_car_replicas",
                            "Capacity at the watch's confidence "
                            "quantile (Monte Carlo, seed-deterministic).",
                            ("watch",),
                        ),
                        "car_prob_fit": registry.gauge(
                            "kccap_car_prob_fit",
                            "Fraction of usage samples whose capacity "
                            "fits the watch's replicas.",
                            ("watch",),
                        ),
                        "car_alert_state": registry.gauge(
                            "kccap_car_alert_state",
                            "Capacity-at-risk watch alert state "
                            "(0=ok, 1=recovered, 2=breached).",
                            ("watch",),
                        ),
                        "car_eval": registry.histogram(
                            "kccap_car_eval_seconds",
                            "Wall time of one capacity-at-risk watch "
                            "evaluation (sampling + sweep + reduction).",
                            ("watch",),
                        ),
                    }
                )
            if self._horizon_names:
                # The forecast family, registered only when a horizon
                # watch exists (same conditional-shape policy as the
                # CaR and gang families above).
                self._m.update(
                    {
                        "forecast_capacity": registry.gauge(
                            "kccap_forecast_capacity",
                            "Minimum projected quantile capacity "
                            "across the watch's forecast horizon.",
                            ("watch",),
                        ),
                        "forecast_ttb": registry.gauge(
                            "kccap_forecast_time_to_breach_seconds",
                            "Projected seconds until the quantile "
                            "capacity first crosses the watch "
                            "threshold (-1 = no breach inside the "
                            "horizon, or no usable trend).",
                            ("watch",),
                        ),
                        "forecast_alert_state": registry.gauge(
                            "kccap_forecast_alert_state",
                            "Forecast watch alert state "
                            "(0=ok, 1=recovered, 2=breached).",
                            ("watch",),
                        ),
                        "forecast_eval": registry.histogram(
                            "kccap_forecast_eval_seconds",
                            "Wall time of one forecast watch "
                            "evaluation (trend fit + one batched "
                            "horizon sweep).",
                            ("watch",),
                        ),
                    }
                )

    # -- observation -------------------------------------------------------
    def observe(
        self, snapshot: ClusterSnapshot, generation: int, *, ts=None
    ) -> GenerationRecord:
        """Evaluate the watchlist against one published generation and
        append the record.  Runs on the PUBLISHER'S thread (for a live
        server, the coalescer worker — never a request dispatcher)."""
        with self._lock:
            t0 = time.perf_counter()
            prev = self._ring[-1] if self._ring else None
            record = GenerationRecord(
                generation=int(generation),
                ts=time.time() if ts is None else float(ts),
                digest=snapshot_digest(snapshot),
                semantics=snapshot.semantics,
                n_nodes=snapshot.n_nodes,
                healthy_nodes=int(np.sum(snapshot.healthy)),
                summary=node_summary(snapshot),
            )
            transitions: list[tuple[str, WatchAlert]] = []
            for mode, specs in self._mode_groups(snapshot):
                plain = [
                    s for s in specs
                    if s.quantile is None and s.gang is None
                ]
                # The same implicit hard-taint mask every strict fit
                # surface applies (None unless the snapshot itself is
                # strict-packed) — so a timeline capacity equals the fit
                # op's answer for the identical spec, bit for bit.
                mask = (
                    implicit_taint_mask(snapshot)
                    if mode == "strict"
                    else None
                )
                if plain:
                    grid = ScenarioGrid.from_scenarios(
                        [s.scenario for s in plain]
                    )
                    result = explain_snapshot(
                        snapshot, grid, mode=mode, node_mask=mask
                    )
                    for s_i, spec in enumerate(plain):
                        total = int(result.totals[s_i])
                        alert = self._alerts[spec.name]
                        transition = alert.update(total, record.generation)
                        if transition is not None:
                            transitions.append((transition, alert))
                        record.watches[spec.name] = WatchResult(
                            name=spec.name,
                            mode=mode,
                            total=total,
                            schedulable=total >= spec.scenario.replicas,
                            breached=total < (spec.min_replicas or 0),
                            min_replicas=spec.min_replicas,
                            binding_counts=result.binding_counts(s_i),
                            fits=np.asarray(result.fits[s_i], dtype=np.int64),
                        )
                for spec in specs:
                    if spec.quantile is None and spec.gang is None:
                        continue
                    if spec.gang is not None:
                        r = self._evaluate_gang(snapshot, spec, mode, mask)
                    elif spec.horizon_steps is not None:
                        r = self._evaluate_horizon_locked(
                            snapshot, spec, mode, mask, record
                        )
                    else:
                        r = self._evaluate_car(snapshot, spec, mode, mask)
                    alert = self._alerts[spec.name]
                    # A forecast watch alerts on the horizon MINIMUM —
                    # "will breach" is the point of a forecast; plain
                    # watches alert on the evaluated total as before.
                    alert_total = (
                        r.horizon_min_capacity
                        if r.horizon_min_capacity is not None
                        else r.total
                    )
                    transition = alert.update(alert_total, record.generation)
                    if transition is not None:
                        transitions.append((transition, alert))
                    record.watches[spec.name] = r
            record.eval_ms = (time.perf_counter() - t0) * 1e3
            self._ring.append(record)
            self._publish_metrics_locked(record, prev)
            self._append_log(record, transitions)
            return record

    def _evaluate_car(
        self, snapshot: ClusterSnapshot, spec: WatchSpec, mode: str, mask
    ) -> WatchResult:
        """One capacity-at-risk watch against one generation.

        The Monte Carlo pass rides the production sweep path (grouped /
        bucketed / cached — seed-deterministic across all of them); the
        watch's "capacity" is the quantile, and the per-node fits /
        binding histogram come from explaining the quantile-realizing
        usage sample, so drift attribution stays node-granular and the
        quantile total equals that explain's fit sum by construction.
        """
        from kubernetesclustercapacity_tpu.stochastic.car import (
            capacity_at_risk,
        )
        from kubernetesclustercapacity_tpu.stochastic.distributions import (
            StochasticSpec,
        )

        s_spec = StochasticSpec(
            cpu=spec.usage_cpu,
            memory=spec.usage_mem,
            replicas=spec.scenario.replicas,
            samples=spec.samples,
            seed=spec.seed,
        )
        res = capacity_at_risk(
            snapshot,
            s_spec,
            mode=mode,
            node_mask=mask,
            quantiles=(spec.quantile,),
            bindings=False,
        )
        total = res.quantiles[spec.quantile]
        q_i = res.quantile_samples[spec.quantile]
        qgrid = ScenarioGrid(
            cpu_request_milli=res.samples_cpu[[q_i]],
            mem_request_bytes=res.samples_mem[[q_i]],
            replicas=np.array([spec.scenario.replicas], dtype=np.int64),
        )
        ex = explain_snapshot(snapshot, qgrid, mode=mode, node_mask=mask)
        return WatchResult(
            name=spec.name,
            mode=mode,
            total=total,
            schedulable=total >= spec.scenario.replicas,
            breached=total < (spec.min_replicas or 0),
            min_replicas=spec.min_replicas,
            binding_counts=ex.binding_counts(0),
            fits=np.asarray(ex.fits[0], dtype=np.int64),
            quantile=spec.quantile,
            prob_fit=res.prob_fit,
            samples=res.n_samples,
            car_eval_ms=res.eval_ms,
        )

    def _evaluate_horizon_locked(
        self,
        snapshot: ClusterSnapshot,
        spec: WatchSpec,
        mode: str,
        mask,
        record: GenerationRecord,
    ) -> WatchResult:
        """One forecast watch against one generation.

        Fits a Theil–Sen demand trend over the timeline's OWN ring
        (the records' observation stamps — never the wall clock at fit
        time, so re-observing the same history re-fits the same trend),
        then projects the watch's usage samples along it as ONE batched
        ``[H×S]`` sweep.  ``total`` stays the h=0 quantile capacity;
        the alert machine thresholds the horizon MINIMUM, and
        ``time_to_breach_s`` says when.  With fewer than 3 ring records
        or a flat/shrinking trend the watch degrades to a plain
        capacity-at-risk evaluation with ``time_to_breach_s = None`` —
        explicitly no forecast, never a fabricated one.
        """
        from kubernetesclustercapacity_tpu.forecast.horizon import (
            project_horizon,
        )
        from kubernetesclustercapacity_tpu.forecast.trend import fit_trend
        from kubernetesclustercapacity_tpu.stochastic.distributions import (
            StochasticSpec,
        )
        from kubernetesclustercapacity_tpu.stochastic.history import (
            InsufficientHistoryError,
        )

        horizon_s = (spec.horizon_steps - 1) * spec.horizon_step_s
        # The ring has not been appended yet — the series is the ring
        # plus the generation under observation.  Summary rows follow
        # diff.NODE_FIELDS order: index 3 = used_cpu_req_milli,
        # index 4 = used_mem_req_bytes.
        recs = list(self._ring) + [record]
        growth_cpu = growth_mem = 0.0
        degraded = False
        fitted = False
        if len(recs) >= 3:
            axis = np.asarray([r.ts for r in recs], dtype=np.float64)
            degraded = bool(
                np.any(np.diff(axis) < 0) or axis[-1] <= axis[0]
            )
            if degraded:
                axis = np.arange(len(recs), dtype=np.float64)
            cpu_tot = [
                float(sum(row[3] for row in r.summary.values()))
                for r in recs
            ]
            mem_tot = [
                float(sum(row[4] for row in r.summary.values()))
                for r in recs
            ]
            try:
                fit_cpu = fit_trend(
                    axis, cpu_tot, degraded_time_axis=degraded
                )
                fit_mem = fit_trend(
                    axis, mem_tot, degraded_time_axis=degraded
                )
                growth_cpu = max(fit_cpu.relative_slope_per_s, 0.0)
                growth_mem = max(fit_mem.relative_slope_per_s, 0.0)
                fitted = True
            except (InsufficientHistoryError, ValueError):
                fitted = False
        if not fitted or (growth_cpu == 0.0 and growth_mem == 0.0):
            # No trend (or a flat/shrinking one): the honest forecast
            # is "no projected breach" — a plain CaR evaluation with an
            # explicit null time-to-breach.
            r = self._evaluate_car(snapshot, spec, mode, mask)
            r.horizon_s = horizon_s
            r.time_to_breach_s = None
            r.horizon_min_capacity = None
            r.degraded_time_axis = degraded
            return r
        s_spec = StochasticSpec(
            cpu=spec.usage_cpu,
            memory=spec.usage_mem,
            replicas=spec.scenario.replicas,
            samples=spec.samples,
            seed=spec.seed,
        )
        threshold = (
            spec.min_replicas
            if spec.min_replicas is not None
            else spec.scenario.replicas
        )
        hr = project_horizon(
            snapshot,
            s_spec,
            steps=spec.horizon_steps,
            step_s=spec.horizon_step_s,
            growth_cpu_per_s=growth_cpu,
            growth_mem_per_s=growth_mem,
            mode=mode,
            node_mask=mask,
            quantiles=(spec.quantile,),
            threshold=threshold,
            degraded_time_axis=degraded,
        )
        total = int(hr.quantiles[spec.quantile][0])
        min_cap = hr.min_capacity(spec.quantile)
        # Node-granular fits/bindings come from the pod-level explain of
        # the watch's own scenario (the gang-watch convention) so delta
        # attribution works unchanged.
        grid = ScenarioGrid.from_scenarios([spec.scenario])
        ex = explain_snapshot(snapshot, grid, mode=mode, node_mask=mask)
        return WatchResult(
            name=spec.name,
            mode=mode,
            total=total,
            schedulable=total >= spec.scenario.replicas,
            breached=min_cap < (spec.min_replicas or 0),
            min_replicas=spec.min_replicas,
            binding_counts=ex.binding_counts(0),
            fits=np.asarray(ex.fits[0], dtype=np.int64),
            quantile=spec.quantile,
            prob_fit=None,
            samples=hr.n_samples,
            car_eval_ms=hr.eval_ms,
            horizon_s=horizon_s,
            time_to_breach_s=hr.time_to_breach_s[spec.quantile],
            horizon_min_capacity=min_cap,
            degraded_time_axis=degraded,
        )

    def _evaluate_gang(
        self, snapshot: ClusterSnapshot, spec: WatchSpec, mode: str, mask
    ) -> WatchResult:
        """One gang watch against one generation: the watch's capacity
        IS the whole-gang count (``min_replicas`` thresholds gangs).
        Per-node fits and the binding histogram come from the pod-level
        explain of the same scenario so delta attribution stays
        node-granular, exactly as CaR watches do."""
        from kubernetesclustercapacity_tpu.topology.gang import gang_explain

        grid = ScenarioGrid.from_scenarios([spec.scenario])
        ex = explain_snapshot(snapshot, grid, mode=mode, node_mask=mask)
        detail = gang_explain(
            snapshot, grid, spec.gang, mode=mode, node_mask=mask
        )
        total = int(detail["gangs"])
        return WatchResult(
            name=spec.name,
            mode=mode,
            total=total,
            schedulable=bool(detail["schedulable"]),
            breached=total < (spec.min_replicas or 0),
            min_replicas=spec.min_replicas,
            binding_counts=ex.binding_counts(0),
            fits=np.asarray(ex.fits[0], dtype=np.int64),
            gang_ranks=spec.gang.ranks,
            gang_count=spec.gang.count,
            gang_binding=detail["binding"],
            gang_summary=detail["summary"],
        )

    def _mode_groups(self, snapshot: ClusterSnapshot):
        """Watches grouped by effective kernel mode (one explain pass per
        mode, whole watchlist vectorized along the scenario axis)."""
        groups: dict[str, list[WatchSpec]] = {}
        for spec in self.watches:
            groups.setdefault(spec.mode or snapshot.semantics, []).append(
                spec
            )
        return groups.items()

    def _publish_metrics_locked(self, record, prev) -> None:
        if self._m is None or not _telemetry_enabled():
            return
        m = self._m
        m["generation"].labels().set(record.generation)
        m["records"].labels().set(len(self._ring))
        m["eval"].observe(record.eval_ms / 1e3)
        for spec in self.watches:
            r = record.watches.get(spec.name)
            if r is None:
                continue
            m["replicas"].labels(watch=spec.name).set(r.total)
            threshold = spec.min_replicas or spec.scenario.replicas
            if threshold > 0:
                m["headroom"].labels(watch=spec.name).set(
                    round(100.0 * (r.total - threshold) / threshold, 4)
                )
            m["alert_state"].labels(watch=spec.name).set(
                self._alerts[spec.name].state_code
            )
            if spec.gang is not None and "gang_capacity" in m:
                m["gang_capacity"].labels(watch=spec.name).set(r.total)
                m["gang_alert_state"].labels(watch=spec.name).set(
                    self._alerts[spec.name].state_code
                )
            if (
                spec.quantile is not None
                and spec.horizon_steps is None
                and "car_replicas" in m
            ):
                m["car_replicas"].labels(watch=spec.name).set(r.total)
                if r.prob_fit is not None:
                    m["car_prob_fit"].labels(watch=spec.name).set(
                        round(r.prob_fit, 6)
                    )
                m["car_alert_state"].labels(watch=spec.name).set(
                    self._alerts[spec.name].state_code
                )
                m["car_eval"].labels(watch=spec.name).observe(
                    r.car_eval_ms / 1e3
                )
            if spec.horizon_steps is not None and "forecast_capacity" in m:
                m["forecast_capacity"].labels(watch=spec.name).set(
                    r.horizon_min_capacity
                    if r.horizon_min_capacity is not None
                    else r.total
                )
                m["forecast_ttb"].labels(watch=spec.name).set(
                    round(r.time_to_breach_s, 3)
                    if r.time_to_breach_s is not None
                    else -1
                )
                m["forecast_alert_state"].labels(watch=spec.name).set(
                    self._alerts[spec.name].state_code
                )
                m["forecast_eval"].labels(watch=spec.name).observe(
                    r.car_eval_ms / 1e3
                )
            before = (
                prev.watches[spec.name].total
                if prev is not None and spec.name in prev.watches
                else None
            )
            if before is not None and r.total != before:
                m["changes"].labels(
                    watch=spec.name,
                    direction="up" if r.total > before else "down",
                ).inc()
        # Breach counters track the alert machine exactly (one source).
        for name, alert in self._alerts.items():
            if alert.breaches:
                c = m["breaches"].labels(watch=name)
                c.inc(alert.breaches - c.value)

    def _append_log(self, record, transitions) -> None:
        if self._log is None:
            return
        try:
            self._log.record(
                kind="generation",
                generation=record.generation,
                ts=record.ts,
                digest=record.digest,
                nodes=record.n_nodes,
                healthy_nodes=record.healthy_nodes,
                watches={
                    name: r.total for name, r in record.watches.items()
                },
                eval_ms=round(record.eval_ms, 3),
            )
            for transition, alert in transitions:
                self._log.record(
                    kind="alert",
                    ts=record.ts,
                    watch=alert.name,
                    transition=transition,
                    generation=record.generation,
                    total=alert.last_total,
                    min_replicas=alert.min_replicas,
                    breaches=alert.breaches,
                )
        except Exception:  # noqa: BLE001 - logging must not fail a publish
            pass

    # -- read surfaces -----------------------------------------------------
    def records(
        self, *, since_generation: int | None = None
    ) -> list[GenerationRecord]:
        """Oldest-to-newest copy of the ring (optionally only generations
        strictly after ``since_generation``)."""
        with self._lock:
            recs = list(self._ring)
        if since_generation is not None:
            recs = [r for r in recs if r.generation > since_generation]
        return recs

    def alerts(self) -> dict[str, dict]:
        """Current alert state per watch (wire shape)."""
        with self._lock:
            return {n: a.to_wire() for n, a in self._alerts.items()}

    def deltas(
        self,
        *,
        since_generation: int | None = None,
        watch: str | None = None,
    ) -> list[dict]:
        """Attributed generation transitions, oldest to newest.

        Each entry joins the node-set diff with per-watch capacity
        movement: binding-constraint shift plus the per-node fit
        contributions (added nodes contribute their new fit, removed
        nodes their lost fit, mutated nodes the difference).
        ``since_generation`` keeps transitions ENDING after it; ``watch``
        filters the per-watch sections.
        """
        with self._lock:
            recs = list(self._ring)
        out = []
        for prev, cur in zip(recs, recs[1:]):
            if (
                since_generation is not None
                and cur.generation <= since_generation
            ):
                continue
            out.append(self._delta(prev, cur, watch))
        return out

    def _delta(self, prev, cur, watch: str | None) -> dict:
        diff = diff_summaries(prev.summary, cur.summary)
        prev_idx = {k: i for i, k in enumerate(prev.summary)}
        cur_idx = {k: i for i, k in enumerate(cur.summary)}
        # Added nodes whose row matches an EXISTING shape: they joined a
        # (shape, count) group rather than introducing a new one — the
        # grouped-dispatch census moved, which the attribution must say
        # even when the node's own fit contribution is zero.
        prev_shapes = set(prev.summary.values())
        shape_joins = {
            key: shape_key(row)
            for key, row in diff.added.items()
            if row in prev_shapes
        }
        watches: dict[str, dict] = {}
        for name, r in cur.watches.items():
            if watch is not None and name != watch:
                continue
            old = prev.watches.get(name)
            if old is None:
                continue
            contributions: list[tuple[str, int, str]] = []
            for key in diff.removed:
                c = -int(old.fits[prev_idx[key]])
                if c:
                    contributions.append((key, c, "removed"))
            for key in diff.added:
                c = int(r.fits[cur_idx[key]])
                if c:
                    contributions.append((key, c, "added"))
            for key in diff.changed:
                c = int(r.fits[cur_idx[key]]) - int(old.fits[prev_idx[key]])
                if c:
                    contributions.append((key, c, "mutated"))
            contributions.sort(key=lambda t: (-abs(t[1]), t[0]))
            shift = binding_shift(old.binding_counts, r.binding_counts)
            watches[name] = {
                "before": old.total,
                "after": r.total,
                "delta": r.total - old.total,
                "binding_shift": shift,
                "contributors": [
                    {"node": k, "delta": c, "change": kind}
                    for k, c, kind in contributions[:_MAX_CONTRIBUTORS]
                ],
                "summary": _delta_summary(
                    name, old.total, r.total, diff, shift, contributions,
                    shape_joins,
                ),
            }
        return {
            "from_generation": prev.generation,
            "to_generation": cur.generation,
            "ts": cur.ts,
            "nodes_added": sorted(diff.added),
            "nodes_removed": sorted(diff.removed),
            "nodes_changed": len(diff.changed),
            "shape_joins": [
                {"node": k, "shape": sk}
                for k, sk in sorted(shape_joins.items())
            ],
            "diff": diff.to_wire(),
            "watches": watches,
        }

    # -- aggregate surfaces ------------------------------------------------
    def wire(
        self,
        *,
        since_generation: int | None = None,
        watch: str | None = None,
    ) -> dict:
        """The whole timeline as the ``timeline`` op's response body."""
        if watch is not None and watch not in self._alerts:
            raise ValueError(
                f"unknown watch {watch!r} "
                f"(have {sorted(self._alerts) or 'none'})"
            )
        records = self.records(since_generation=since_generation)
        with self._lock:
            count, last = len(self._ring), (
                self._ring[-1].generation if self._ring else 0
            )
        return {
            "enabled": True,
            "depth": self.depth,
            "count": count,
            "generation": last,
            "watchlist": [w.to_wire() for w in self.watches],
            "records": [r.to_wire(watch) for r in records],
            "deltas": self.deltas(
                since_generation=since_generation, watch=watch
            ),
            "alerts": (
                self.alerts()
                if watch is None
                else {watch: self.alerts()[watch]}
            ),
        }

    def car_breached(self) -> list[str]:
        """Capacity-at-risk watches currently breached — the slice of
        alert state that flips ``/healthz`` to 503 (a quantile watch
        breach is a confidence statement: "with 95% confidence fewer
        than N replicas fit", which a load balancer must see)."""
        if not self._car_names:
            return []
        with self._lock:
            return sorted(
                n
                for n, a in self._alerts.items()
                if n in self._car_names and a.state == "breached"
            )

    def gang_breached(self) -> list[str]:
        """Gang watches currently breached — the slice of alert state
        that flips ``/healthz`` to 503 (like :meth:`car_breached`: a
        breached gang watch says fewer than N whole gangs fit, which a
        gang-scheduling admission plane must see, not discover)."""
        if not self._gang_names:
            return []
        with self._lock:
            return sorted(
                n
                for n, a in self._alerts.items()
                if n in self._gang_names and a.state == "breached"
            )

    def forecast_breached(self) -> list[str]:
        """Forecast watches currently breached — the slice of alert
        state that flips ``/healthz`` to 503 (like :meth:`car_breached`:
        a breached forecast says the projected quantile capacity
        crosses the threshold INSIDE the horizon — the one alert whose
        whole value is arriving before the outage does)."""
        if not self._horizon_names:
            return []
        with self._lock:
            return sorted(
                n
                for n, a in self._alerts.items()
                if n in self._horizon_names and a.state == "breached"
            )

    def forecast_status(self) -> dict:
        """Per-forecast-watch status (the ``forecast`` op's watch view /
        the doctor's "capacity forecast" line): last h=0 and horizon-
        minimum quantile capacities, time to breach, alert state."""
        with self._lock:
            last = self._ring[-1] if self._ring else None
            out: dict[str, dict] = {}
            for spec in self.watches:
                if spec.horizon_steps is None:
                    continue
                r = last.watches.get(spec.name) if last else None
                out[spec.name] = {
                    "quantile": spec.quantile,
                    "min_replicas": spec.min_replicas,
                    "steps": spec.horizon_steps,
                    "step_s": spec.horizon_step_s,
                    "horizon_s": (spec.horizon_steps - 1)
                    * spec.horizon_step_s,
                    "last_total": r.total if r else None,
                    "horizon_min_capacity": (
                        r.horizon_min_capacity if r else None
                    ),
                    "time_to_breach_s": (
                        r.time_to_breach_s if r else None
                    ),
                    "degraded_time_axis": (
                        r.degraded_time_axis if r else False
                    ),
                    "samples": r.samples if r else 0,
                    "seed": spec.seed,
                    "alert": self._alerts[spec.name].to_wire(),
                }
            return out

    def gang_status(self) -> dict:
        """Per-gang-watch status (the ``gang`` op's watch view / the
        doctor's "gang capacity" line): last whole-gang count, the
        binding topology level, and alert state."""
        with self._lock:
            last = self._ring[-1] if self._ring else None
            out: dict[str, dict] = {}
            for spec in self.watches:
                if spec.gang is None:
                    continue
                r = last.watches.get(spec.name) if last else None
                out[spec.name] = {
                    "ranks": spec.gang.ranks,
                    "count": spec.gang.count,
                    "colocate": spec.gang.colocate,
                    "min_replicas": spec.min_replicas,
                    "last_gangs": r.total if r else None,
                    "binding": r.gang_binding if r else None,
                    "summary": r.gang_summary if r else "",
                    "alert": self._alerts[spec.name].to_wire(),
                }
            return out

    def car_status(self) -> dict:
        """Per-CaR-watch status (the ``car`` op's watch view / the
        doctor's "capacity at risk" line): last quantile capacity,
        probability-of-fit, sample count, alert state."""
        with self._lock:
            last = self._ring[-1] if self._ring else None
            out: dict[str, dict] = {}
            for spec in self.watches:
                if spec.quantile is None or spec.horizon_steps is not None:
                    # Horizon watches report under forecast_status —
                    # each watch belongs to exactly one funnel.
                    continue
                r = last.watches.get(spec.name) if last else None
                out[spec.name] = {
                    "quantile": spec.quantile,
                    "min_replicas": spec.min_replicas,
                    "last_total": r.total if r else None,
                    "prob_fit": (
                        round(r.prob_fit, 6)
                        if r and r.prob_fit is not None
                        else None
                    ),
                    "samples": r.samples if r else 0,
                    "seed": spec.seed,
                    "alert": self._alerts[spec.name].to_wire(),
                }
            return out

    def stats(self) -> dict:
        """Compact health view (doctor / ``/healthz``)."""
        with self._lock:
            count = len(self._ring)
            last = self._ring[-1] if self._ring else None
            alerts = {n: a.state for n, a in self._alerts.items()}
        out = {
            "records": count,
            "depth": self.depth,
            "generation": last.generation if last else 0,
            "watches": [w.name for w in self.watches],
            "alerts": alerts,
            "breached": sorted(
                n for n, s in alerts.items() if s == "breached"
            ),
            "last_eval_ms": round(last.eval_ms, 3) if last else None,
        }
        if self._car_names:
            # Present only when quantile watches exist, so a plain
            # timeline's stats shape stays byte-identical to pre-CaR.
            out["car_breached"] = sorted(
                n
                for n, s in alerts.items()
                if n in self._car_names and s == "breached"
            )
        if self._gang_names:
            # Same shape policy: the gang slice appears only when gang
            # watches exist.
            out["gang_breached"] = sorted(
                n
                for n, s in alerts.items()
                if n in self._gang_names and s == "breached"
            )
        if self._horizon_names:
            # And the forecast slice only when horizon watches exist.
            out["forecast_breached"] = sorted(
                n
                for n, s in alerts.items()
                if n in self._horizon_names and s == "breached"
            )
        return out

    def close(self) -> None:
        if self._log is not None:
            self._log.close()
