"""Watchlists: the named scenarios a timeline re-evaluates every generation.

A watchlist file (``kccap-server -watch FILE``, YAML or JSON — YAML is a
superset, so one loader serves both) names the what-if specs an operator
actually cares about, in the reference CLI's own flag grammar::

    watches:
      - name: web-tier
        pod:
          cpuRequests: 500m
          memRequests: 1gb
          replicas: "40"
        min_replicas: 30        # optional alert threshold
      - name: batch-strict
        pod: {cpuRequests: "2", memRequests: 4gb}
        semantics: strict       # optional kernel-mode override

``pod`` fields parse through :func:`~..scenario.scenario_from_flags` —
the exact reference codecs, so a watch capacity is bit-identical to the
``kccap`` fit of the same flags.  ``semantics`` overrides the evaluation
mode for that watch (default: the served snapshot's own packing mode);
``min_replicas`` arms the ok → breached → recovered alert machine
(absent = the watch is observed but never alerts).

**Capacity-at-risk watches**: a ``quantile`` field turns a watch
stochastic — "alert when P95 capacity < N"::

    watches:
      - name: web-p95
        pod: {cpuRequests: 500m, memRequests: 1gb, replicas: "40"}
        quantile: 0.95          # capacity at 95% confidence
        usage:                  # per-pod usage distributions
          cpu: {dist: normal, mean: 500m, std: 150m}
          # memory defaults to a point at the pod's memRequests
        samples: 128            # optional Monte Carlo draw count
        seed: 7                 # optional; explicit, never wall-clock
        min_replicas: 30

``quantile`` must lie strictly inside ``(0, 1)`` and REQUIRES a
``usage`` block with at least one non-degenerate distribution — a
point-distribution watch has no usage uncertainty, so every quantile
would silently equal the plain fit (rejected with a clear error rather
than reported as a lie).  A resource omitted from ``usage`` defaults
to a point distribution at the pod's own request.

**Gang watches**: a ``gang`` block makes the watch count WHOLE GANGS
of the pod spec instead of independent replicas — "alert when fewer
than 2 rack-co-located 64-rank gangs fit"::

    watches:
      - name: train-64
        pod: {cpuRequests: "4", memRequests: 8gb}
        gang:
          ranks: 64
          count: 2              # gangs requested (schedulability)
          colocate: rack        # optional: host|rack|zone
          max_ranks_per_domain: 8   # optional, with spread_level
          spread_level: host
        min_replicas: 1         # alert threshold, in WHOLE GANGS

The block parses through :func:`~..topology.gang.parse_gang_block`
(same grammar as the ``gang`` service op and ``kccap -gang-spec``);
``gang`` and ``quantile`` are mutually exclusive — a stochastic gang
watch would need a semantics nobody has defined, so it is rejected,
not guessed.

**Forecast (horizon) watches**: a ``horizon`` block turns a
capacity-at-risk watch predictive — "alert when the P95 capacity is
forecast to cross ``min_replicas`` anywhere inside the horizon"::

    watches:
      - name: web-p95-weekly
        pod: {cpuRequests: 500m, memRequests: 1gb, replicas: "40"}
        quantile: 0.95
        usage:
          cpu: {dist: normal, mean: 500m, std: 150m}
        horizon:
          steps: 24             # projection steps (default 16)
          step_s: 3600          # seconds per step (default 3600)
        min_replicas: 30

The timeline fits a Theil–Sen demand trend over its OWN generation
ring (record timestamps, never the wall clock), projects the watch's
usage samples along it, and breaches on the MINIMUM projected quantile
capacity across the horizon — surfacing ``time_to_breach_s`` on the
watch result.  ``horizon`` requires ``quantile`` and is mutually
exclusive with ``gang``; unlike a plain capacity-at-risk watch,
all-point usage IS allowed here (growth scaling makes even a point
vary across the horizon).
"""

from __future__ import annotations

import json
from dataclasses import dataclass

from kubernetesclustercapacity_tpu.scenario import (
    Scenario,
    ScenarioError,
    scenario_from_flags,
)
from kubernetesclustercapacity_tpu.stochastic.distributions import (
    DistributionError,
    UsageDistribution,
    parse_distribution,
)

__all__ = ["WatchError", "WatchSpec", "load_watchlist", "parse_watchlist"]

_MAX_WATCH_SAMPLES = 1 << 14

# The reference's five flag spellings, the only keys a pod block accepts —
# an unknown key is a typo'd watch that would silently evaluate defaults.
_POD_KEYS = frozenset(
    {"cpuRequests", "cpuLimits", "memRequests", "memLimits", "replicas"}
)

_MODES = ("reference", "strict")


class WatchError(ValueError):
    """Malformed watchlist file/entry (bad YAML/JSON, bad flags, dupes)."""


@dataclass(frozen=True)
class WatchSpec:
    """One named scenario: what to evaluate, how, and when to alert.

    ``quantile`` (with its ``usage`` distributions) makes the watch a
    capacity-at-risk watch: its evaluated "capacity" is the Monte Carlo
    capacity quantile, and ``min_replicas`` breaches against THAT
    ("alert when P95 capacity < N").
    """

    name: str
    scenario: Scenario
    mode: str | None = None  # None = the served snapshot's semantics
    min_replicas: int | None = None
    quantile: float | None = None
    usage_cpu: UsageDistribution | None = None
    usage_mem: UsageDistribution | None = None
    samples: int = 0  # 0 = the process default (KCCAP_CAR_SAMPLES/64)
    seed: int = 0
    #: Gang watch: capacity counted in whole gangs of the pod spec
    #: (a :class:`~..topology.gang.GangSpec`); ``min_replicas`` then
    #: thresholds GANGS, not pods.
    gang: object | None = None
    #: Forecast watch: project the quantile capacity ``horizon_steps``
    #: steps of ``horizon_step_s`` seconds ahead along the timeline's
    #: fitted demand trend; breach on the horizon MINIMUM.
    horizon_steps: int | None = None
    horizon_step_s: float = 3600.0

    def to_wire(self) -> dict:
        """JSON-able description (rides the ``timeline`` op)."""
        out = {
            "name": self.name,
            "cpu_request_milli": self.scenario.cpu_request_milli,
            "mem_request_bytes": self.scenario.mem_request_bytes,
            "replicas": self.scenario.replicas,
            "mode": self.mode,
            "min_replicas": self.min_replicas,
        }
        if self.gang is not None:
            out["gang"] = self.gang.to_wire()
        if self.quantile is not None:
            out["quantile"] = self.quantile
            out["samples"] = self.samples
            out["seed"] = self.seed
            out["usage"] = {
                "cpu": self.usage_cpu.to_wire(),
                "memory": self.usage_mem.to_wire(),
            }
        if self.horizon_steps is not None:
            out["horizon"] = {
                "steps": self.horizon_steps,
                "step_s": self.horizon_step_s,
            }
        return out


def _parse_entry(i: int, entry) -> WatchSpec:
    if not isinstance(entry, dict):
        raise WatchError(f"watch #{i}: expected a mapping, got {entry!r}")
    name = entry.get("name")
    if not isinstance(name, str) or not name:
        raise WatchError(f"watch #{i}: 'name' must be a non-empty string")
    pod = entry.get("pod") or {}
    if not isinstance(pod, dict):
        raise WatchError(f"watch {name!r}: 'pod' must be a mapping")
    unknown = set(pod) - _POD_KEYS
    if unknown:
        raise WatchError(
            f"watch {name!r}: unknown pod field(s) {sorted(unknown)} "
            f"(want {sorted(_POD_KEYS)})"
        )
    try:
        # YAML scalars may arrive as ints (replicas: 40) — the reference
        # grammar is string flags, so stringify before the codec.
        scenario = scenario_from_flags(
            **{k: str(v) for k, v in pod.items()}
        )
        scenario.validate()
    except ScenarioError as e:
        raise WatchError(f"watch {name!r}: bad pod spec: {e}") from e
    mode = entry.get("semantics")
    if mode is not None and mode not in _MODES:
        raise WatchError(
            f"watch {name!r}: semantics must be one of {_MODES}, got {mode!r}"
        )
    min_replicas = entry.get("min_replicas")
    if min_replicas is not None:
        if not isinstance(min_replicas, int) or isinstance(min_replicas, bool):
            raise WatchError(
                f"watch {name!r}: min_replicas must be an integer"
            )
        if min_replicas < 0:
            raise WatchError(
                f"watch {name!r}: min_replicas must be >= 0"
            )
    extra = set(entry) - {
        "name", "pod", "semantics", "min_replicas",
        "quantile", "usage", "samples", "seed", "gang", "horizon",
    }
    if extra:
        raise WatchError(
            f"watch {name!r}: unknown field(s) {sorted(extra)}"
        )
    gang = None
    if "gang" in entry:
        from kubernetesclustercapacity_tpu.topology.gang import (
            GangSpecError,
            parse_gang_block,
        )

        if "quantile" in entry:
            raise WatchError(
                f"watch {name!r}: 'gang' and 'quantile' are mutually "
                "exclusive (stochastic gang capacity is undefined — "
                "pick one)"
            )
        if "horizon" in entry:
            raise WatchError(
                f"watch {name!r}: 'gang' and 'horizon' are mutually "
                "exclusive (a forecast projects usage quantiles, not "
                "gang packings — pick one)"
            )
        try:
            gang = parse_gang_block(entry["gang"])
        except GangSpecError as e:
            raise WatchError(f"watch {name!r}: {e}") from e
    horizon_steps, horizon_step_s = _parse_horizon_block(name, entry)
    quantile, usage_cpu, usage_mem, samples, seed = _parse_stochastic_fields(
        name, entry, scenario, has_horizon=horizon_steps is not None
    )
    return WatchSpec(
        name=name, scenario=scenario, mode=mode, min_replicas=min_replicas,
        quantile=quantile, usage_cpu=usage_cpu, usage_mem=usage_mem,
        samples=samples, seed=seed, gang=gang,
        horizon_steps=horizon_steps, horizon_step_s=horizon_step_s,
    )


def _parse_horizon_block(name: str, entry: dict) -> tuple[int | None, float]:
    """The forecast grammar of one watch entry: ``horizon`` with
    optional ``steps``/``step_s``.  Requires ``quantile`` (a forecast
    projects a quantile, not a point fit); bounds come from
    :func:`~..forecast.horizon.max_steps` so a watchlist cannot smuggle
    in a sweep the server would refuse as a one-shot op."""
    if "horizon" not in entry:
        return None, 3600.0
    if "quantile" not in entry:
        raise WatchError(
            f"watch {name!r}: 'horizon' requires a 'quantile' — a "
            "forecast projects a capacity quantile over time"
        )
    block = entry["horizon"]
    if block is None:
        block = {}
    if not isinstance(block, dict):
        raise WatchError(
            f"watch {name!r}: 'horizon' must be a mapping, got {block!r}"
        )
    unknown = set(block) - {"steps", "step_s"}
    if unknown:
        raise WatchError(
            f"watch {name!r}: unknown horizon field(s) {sorted(unknown)} "
            "(want steps/step_s)"
        )
    from kubernetesclustercapacity_tpu.forecast.horizon import (
        DEFAULT_STEPS,
        max_steps,
    )

    steps = block.get("steps", DEFAULT_STEPS)
    if isinstance(steps, bool) or not isinstance(steps, int):
        raise WatchError(f"watch {name!r}: horizon.steps must be an integer")
    cap = max_steps()
    if not 1 <= steps <= cap:
        raise WatchError(
            f"watch {name!r}: horizon.steps must be in [1, {cap}], "
            f"got {steps}"
        )
    step_s = block.get("step_s", 3600.0)
    if isinstance(step_s, bool) or not isinstance(step_s, (int, float)):
        raise WatchError(f"watch {name!r}: horizon.step_s must be a number")
    step_s = float(step_s)
    if not step_s > 0.0:
        raise WatchError(
            f"watch {name!r}: horizon.step_s must be > 0, got {step_s:g}"
        )
    return steps, step_s


def _parse_stochastic_fields(
    name: str, entry: dict, scenario: Scenario, *, has_horizon: bool = False
):
    """The capacity-at-risk grammar of one watch entry: ``quantile``
    (strictly inside (0, 1)), ``usage`` distributions (missing
    resources default to a point at the pod's own request), ``samples``
    and ``seed``.  Hard rejections — quantile without usage, usage
    without quantile, out-of-range quantiles, all-point usage — each
    with an error naming the watch, so a typo'd watch never silently
    evaluates as something else.  A ``horizon`` watch relaxes the
    usage requirements: growth scaling makes even a point distribution
    vary across the projection, so all-point (or absent) usage is
    meaningful there."""
    quantile = entry.get("quantile")
    usage = entry.get("usage")
    if quantile is None:
        for field in ("usage", "samples", "seed"):
            if field in entry:
                raise WatchError(
                    f"watch {name!r}: '{field}' requires a 'quantile' "
                    "(only capacity-at-risk watches sample usage)"
                )
        return None, None, None, 0, 0
    if isinstance(quantile, bool) or not isinstance(quantile, (int, float)):
        raise WatchError(
            f"watch {name!r}: quantile must be a number in (0, 1), "
            f"got {quantile!r}"
        )
    quantile = float(quantile)
    if not 0.0 < quantile < 1.0:
        raise WatchError(
            f"watch {name!r}: quantile must be strictly inside (0, 1), "
            f"got {quantile:g}"
        )
    if usage is None and not has_horizon:
        raise WatchError(
            f"watch {name!r}: quantile needs a 'usage' distribution "
            "block — a point-request watch has no usage uncertainty, so "
            "every quantile would equal the plain fit"
        )
    if usage is None:
        usage = {}
    if not isinstance(usage, dict):
        raise WatchError(f"watch {name!r}: 'usage' must be a mapping")
    extra = set(usage) - {"cpu", "memory"}
    if extra:
        raise WatchError(
            f"watch {name!r}: unknown usage resource(s) {sorted(extra)} "
            "(want cpu/memory)"
        )
    from kubernetesclustercapacity_tpu.utils.quantity import int64_bits

    try:
        # Defaults are a point at the pod's own request, on the kernel's
        # int64 carrier (wrapped uint64 cpu requests keep the reference
        # meaning: a huge divisor that fits 0 everywhere).
        usage_cpu = (
            parse_distribution("cpu", usage["cpu"])
            if "cpu" in usage
            else UsageDistribution(
                kind="point", value=int64_bits(scenario.cpu_request_milli)
            )
        )
        usage_mem = (
            parse_distribution("memory", usage["memory"])
            if "memory" in usage
            else UsageDistribution(
                kind="point", value=scenario.mem_request_bytes
            )
        )
    except DistributionError as e:
        raise WatchError(f"watch {name!r}: {e}") from e
    if usage_cpu.degenerate and usage_mem.degenerate and not has_horizon:
        raise WatchError(
            f"watch {name!r}: every usage distribution is a point — the "
            f"P{quantile * 100:g} capacity would always equal the plain "
            "fit; drop 'quantile' or give cpu/memory real spread"
        )
    samples = entry.get("samples", 0)
    if isinstance(samples, bool) or not isinstance(samples, int):
        raise WatchError(f"watch {name!r}: samples must be an integer")
    if samples and not 2 <= samples <= _MAX_WATCH_SAMPLES:
        raise WatchError(
            f"watch {name!r}: samples must be in "
            f"[2, {_MAX_WATCH_SAMPLES}], got {samples}"
        )
    seed = entry.get("seed", 0)
    if isinstance(seed, bool) or not isinstance(seed, int):
        raise WatchError(f"watch {name!r}: seed must be an integer")
    return quantile, usage_cpu, usage_mem, samples, seed


def parse_watchlist(data) -> tuple[WatchSpec, ...]:
    """Parsed document (``{"watches": [...]}`` or a bare list) → specs."""
    if isinstance(data, dict):
        entries = data.get("watches")
        extra = set(data) - {"watches"}
        if extra:
            raise WatchError(f"unknown top-level field(s) {sorted(extra)}")
    else:
        entries = data
    if not isinstance(entries, list) or not entries:
        raise WatchError(
            "watchlist wants a non-empty 'watches' list (or a bare list)"
        )
    specs = tuple(_parse_entry(i, e) for i, e in enumerate(entries))
    names = [s.name for s in specs]
    if len(set(names)) != len(names):
        dupes = sorted({n for n in names if names.count(n) > 1})
        raise WatchError(f"duplicate watch name(s): {dupes}")
    return specs


def load_watchlist(path: str) -> tuple[WatchSpec, ...]:
    """Load ``path`` (YAML when PyYAML is present, else strict JSON).

    YAML is a superset of JSON, so a ``.json`` watchlist parses either
    way; without PyYAML only JSON files load (gated, not required).
    """
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    try:
        import yaml  # type: ignore[import-untyped]

        data = yaml.safe_load(text)
    except ImportError:
        try:
            data = json.loads(text)
        except ValueError as e:
            raise WatchError(
                f"{path}: not valid JSON (and PyYAML is unavailable): {e}"
            ) from e
    except Exception as e:  # yaml.YAMLError — malformed document
        raise WatchError(f"{path}: cannot parse: {e}") from e
    return parse_watchlist(data)
