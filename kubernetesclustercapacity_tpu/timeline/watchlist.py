"""Watchlists: the named scenarios a timeline re-evaluates every generation.

A watchlist file (``kccap-server -watch FILE``, YAML or JSON — YAML is a
superset, so one loader serves both) names the what-if specs an operator
actually cares about, in the reference CLI's own flag grammar::

    watches:
      - name: web-tier
        pod:
          cpuRequests: 500m
          memRequests: 1gb
          replicas: "40"
        min_replicas: 30        # optional alert threshold
      - name: batch-strict
        pod: {cpuRequests: "2", memRequests: 4gb}
        semantics: strict       # optional kernel-mode override

``pod`` fields parse through :func:`~..scenario.scenario_from_flags` —
the exact reference codecs, so a watch capacity is bit-identical to the
``kccap`` fit of the same flags.  ``semantics`` overrides the evaluation
mode for that watch (default: the served snapshot's own packing mode);
``min_replicas`` arms the ok → breached → recovered alert machine
(absent = the watch is observed but never alerts).
"""

from __future__ import annotations

import json
from dataclasses import dataclass

from kubernetesclustercapacity_tpu.scenario import (
    Scenario,
    ScenarioError,
    scenario_from_flags,
)

__all__ = ["WatchError", "WatchSpec", "load_watchlist", "parse_watchlist"]

# The reference's five flag spellings, the only keys a pod block accepts —
# an unknown key is a typo'd watch that would silently evaluate defaults.
_POD_KEYS = frozenset(
    {"cpuRequests", "cpuLimits", "memRequests", "memLimits", "replicas"}
)

_MODES = ("reference", "strict")


class WatchError(ValueError):
    """Malformed watchlist file/entry (bad YAML/JSON, bad flags, dupes)."""


@dataclass(frozen=True)
class WatchSpec:
    """One named scenario: what to evaluate, how, and when to alert."""

    name: str
    scenario: Scenario
    mode: str | None = None  # None = the served snapshot's semantics
    min_replicas: int | None = None

    def to_wire(self) -> dict:
        """JSON-able description (rides the ``timeline`` op)."""
        return {
            "name": self.name,
            "cpu_request_milli": self.scenario.cpu_request_milli,
            "mem_request_bytes": self.scenario.mem_request_bytes,
            "replicas": self.scenario.replicas,
            "mode": self.mode,
            "min_replicas": self.min_replicas,
        }


def _parse_entry(i: int, entry) -> WatchSpec:
    if not isinstance(entry, dict):
        raise WatchError(f"watch #{i}: expected a mapping, got {entry!r}")
    name = entry.get("name")
    if not isinstance(name, str) or not name:
        raise WatchError(f"watch #{i}: 'name' must be a non-empty string")
    pod = entry.get("pod") or {}
    if not isinstance(pod, dict):
        raise WatchError(f"watch {name!r}: 'pod' must be a mapping")
    unknown = set(pod) - _POD_KEYS
    if unknown:
        raise WatchError(
            f"watch {name!r}: unknown pod field(s) {sorted(unknown)} "
            f"(want {sorted(_POD_KEYS)})"
        )
    try:
        # YAML scalars may arrive as ints (replicas: 40) — the reference
        # grammar is string flags, so stringify before the codec.
        scenario = scenario_from_flags(
            **{k: str(v) for k, v in pod.items()}
        )
        scenario.validate()
    except ScenarioError as e:
        raise WatchError(f"watch {name!r}: bad pod spec: {e}") from e
    mode = entry.get("semantics")
    if mode is not None and mode not in _MODES:
        raise WatchError(
            f"watch {name!r}: semantics must be one of {_MODES}, got {mode!r}"
        )
    min_replicas = entry.get("min_replicas")
    if min_replicas is not None:
        if not isinstance(min_replicas, int) or isinstance(min_replicas, bool):
            raise WatchError(
                f"watch {name!r}: min_replicas must be an integer"
            )
        if min_replicas < 0:
            raise WatchError(
                f"watch {name!r}: min_replicas must be >= 0"
            )
    extra = set(entry) - {"name", "pod", "semantics", "min_replicas"}
    if extra:
        raise WatchError(
            f"watch {name!r}: unknown field(s) {sorted(extra)}"
        )
    return WatchSpec(
        name=name, scenario=scenario, mode=mode, min_replicas=min_replicas
    )


def parse_watchlist(data) -> tuple[WatchSpec, ...]:
    """Parsed document (``{"watches": [...]}`` or a bare list) → specs."""
    if isinstance(data, dict):
        entries = data.get("watches")
        extra = set(data) - {"watches"}
        if extra:
            raise WatchError(f"unknown top-level field(s) {sorted(extra)}")
    else:
        entries = data
    if not isinstance(entries, list) or not entries:
        raise WatchError(
            "watchlist wants a non-empty 'watches' list (or a bare list)"
        )
    specs = tuple(_parse_entry(i, e) for i, e in enumerate(entries))
    names = [s.name for s in specs]
    if len(set(names)) != len(names):
        dupes = sorted({n for n in names if names.count(n) > 1})
        raise WatchError(f"duplicate watch name(s): {dupes}")
    return specs


def load_watchlist(path: str) -> tuple[WatchSpec, ...]:
    """Load ``path`` (YAML when PyYAML is present, else strict JSON).

    YAML is a superset of JSON, so a ``.json`` watchlist parses either
    way; without PyYAML only JSON files load (gated, not required).
    """
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    try:
        import yaml  # type: ignore[import-untyped]

        data = yaml.safe_load(text)
    except ImportError:
        try:
            data = json.loads(text)
        except ValueError as e:
            raise WatchError(
                f"{path}: not valid JSON (and PyYAML is unavailable): {e}"
            ) from e
    except Exception as e:  # yaml.YAMLError — malformed document
        raise WatchError(f"{path}: cannot parse: {e}") from e
    return parse_watchlist(data)
