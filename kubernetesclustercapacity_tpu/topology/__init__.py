"""Gang & topology-aware capacity (zone/rack/host hierarchy).

:mod:`.model` parses node labels into dense small-int topology code
columns (the segmented-reduction index space); :mod:`.gang` counts
WHOLE gangs — all-or-nothing groups of co-scheduled ranks — under
co-location and rank-aware spread constraints, bit-exact against a
pure numpy/Python oracle on every dispatch path.
"""

from kubernetesclustercapacity_tpu.topology.gang import (
    GangResult,
    GangSpec,
    GangSpecError,
    gang_capacity,
    gang_explain,
    gang_grouped_enabled,
    gang_oracle,
    gang_spec_from_msg,
    load_gang_spec,
    parse_gang_block,
)
from kubernetesclustercapacity_tpu.topology.model import (
    DEFAULT_HOST_KEY,
    DEFAULT_RACK_KEY,
    DEFAULT_ZONE_KEY,
    LEVELS,
    ClusterTopology,
    TopologyKeys,
    attach_topology,
    label_codes,
    node_name_index,
    topology_from_snapshot,
)

__all__ = [
    "LEVELS",
    "DEFAULT_ZONE_KEY",
    "DEFAULT_RACK_KEY",
    "DEFAULT_HOST_KEY",
    "TopologyKeys",
    "ClusterTopology",
    "label_codes",
    "node_name_index",
    "topology_from_snapshot",
    "attach_topology",
    "GangSpec",
    "GangSpecError",
    "GangResult",
    "gang_capacity",
    "gang_explain",
    "gang_oracle",
    "gang_spec_from_msg",
    "load_gang_spec",
    "parse_gang_block",
    "gang_grouped_enabled",
]
