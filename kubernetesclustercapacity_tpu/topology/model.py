"""Topology model (zone/rack/host hierarchy) on the node axis.

The snapshot's node labels carry a physical hierarchy — zone, rack,
host — that the reference (and every layer before this PR) ignored.
Gang scheduling needs it as *array* data: this module parses the
hierarchy from labels into dense small-int **code columns** on the node
axis (``codes[n]`` = the node's domain index at one level, ``-1`` =
excluded), the TPU-native form every gang kernel consumes as a
segmented-reduction index.

Three levels, finest first — :data:`LEVELS` ``("host", "rack", "zone")``
— read from configurable label keys (:class:`TopologyKeys`; defaults are
the upstream well-known keys).  Domains NEST: a rack domain is keyed by
its ``(zone label, rack label)`` pair and a host domain by the full
triple, so ``rack=r0`` in two different zones is two domains (the
hierarchy stays a tree even when label values repeat across parents).

Missing labels are an explicit policy, never a silent default
(:func:`label_codes` ``missing=``):

* ``"own"`` (the topology-model default) — an unlabeled node forms its
  own singleton domain (named ``~node:<row>``): it still holds ranks,
  it just shares a domain with nobody.  The natural reading for the
  host level, where a missing hostname label means "this node is its
  own host".
* ``"exclude"`` — an unlabeled node gets code ``-1``: it belongs to no
  domain and contributes nothing to any domain-level capacity.  This is
  the policy :meth:`~..models.capacity.CapacityModel.topology_spread`
  has always applied to unkeyed nodes (they are counted and reported,
  never summed), now routed through the same helper so the two surfaces
  cannot drift.

This module is also the package's ONE home for hostname-identity
helpers: :func:`node_name_index` (the name→row map the anti-affinity
mask's hostname topology uses) lives here so ``masks.py`` and the gang
model resolve node identity through the same rule.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

__all__ = [
    "LEVELS",
    "DEFAULT_ZONE_KEY",
    "DEFAULT_RACK_KEY",
    "DEFAULT_HOST_KEY",
    "TopologyKeys",
    "ClusterTopology",
    "label_codes",
    "node_name_index",
    "topology_from_snapshot",
    "attach_topology",
]

#: Hierarchy levels, finest first.  ``None`` (no level) means
#: cluster-wide in every consumer.
LEVELS = ("host", "rack", "zone")

#: Position in the hierarchy (0 = finest).  Shared by GangSpec
#: validation ("spread level must be strictly finer than the
#: co-location level") and the explain surface's level ordering.
LEVEL_ORDER = {level: i for i, level in enumerate(LEVELS)}

DEFAULT_ZONE_KEY = "topology.kubernetes.io/zone"
DEFAULT_RACK_KEY = "topology.kubernetes.io/rack"
DEFAULT_HOST_KEY = "kubernetes.io/hostname"

_MISSING_POLICIES = ("own", "exclude")


@dataclass(frozen=True)
class TopologyKeys:
    """The node-label keys the hierarchy parses from (configurable —
    clouds that label racks as ``failure-domain.beta...`` or zones under
    the legacy key swap them here, nothing downstream changes)."""

    zone: str = DEFAULT_ZONE_KEY
    rack: str = DEFAULT_RACK_KEY
    host: str = DEFAULT_HOST_KEY


def label_codes(
    labels,
    key: str,
    *,
    missing: str = "own",
    eligible=None,
    n_nodes: int | None = None,
):
    """THE label→code helper: one level's label values → dense codes.

    Returns ``(codes[N] int64, domains, missing_count)`` — ``domains``
    is the value list in first-eligible-row order (``codes[i]`` indexes
    it), ``missing_count`` how many eligible rows lacked the key.

    ``labels`` is the snapshot's per-node label-dict list (rows beyond
    its length count as unlabeled — fixture-less snapshots carry an
    empty list); ``eligible`` (``[N]`` bool, optional) restricts which
    rows mint domains at all — an ineligible row keeps code ``-1`` and
    is NOT counted as missing, exactly the membership rule
    ``CapacityModel.topology_spread`` has always applied.  ``missing``
    picks the unlabeled-row policy documented in the module docstring.
    """
    if missing not in _MISSING_POLICIES:
        raise ValueError(
            f"missing-label policy must be one of {_MISSING_POLICIES}, "
            f"got {missing!r}"
        )
    n = len(labels) if n_nodes is None else int(n_nodes)
    codes = np.full(n, -1, dtype=np.int64)
    domains: list = []
    ids: dict = {}
    missing_count = 0
    for i in range(n):
        if eligible is not None and not eligible[i]:
            continue
        row = labels[i] if i < len(labels) else None
        value = (row or {}).get(key)
        if value is None:
            missing_count += 1
            if missing == "own":
                codes[i] = len(domains)
                domains.append(f"~node:{i}")
            continue
        code = ids.get(value)
        if code is None:
            code = ids[value] = len(domains)
            domains.append(value)
        codes[i] = code
    return codes, domains, missing_count


def node_name_index(snapshot) -> dict[str, int]:
    """Node name → row index — the hostname-identity rule shared by the
    anti-affinity mask's hostname topology and the topology model.

    Duplicate names keep the LAST row (dict-comprehension semantics,
    pinned by tests: the pre-topology ``masks.py`` behaved this way and
    reference-mode phantom rows all share the ``""`` key); a pod naming
    a node outside this map is excluded from hostname-topology effects.
    """
    return {name: i for i, name in enumerate(snapshot.names)}


@dataclass
class ClusterTopology:
    """Dense topology-code columns for one snapshot.

    ``codes(level)`` is the ``[N]`` int64 domain index at that level
    (``-1`` = excluded under the ``"exclude"`` policy);
    ``domains(level)`` the human names, indexable by code.  Codes NEST:
    :meth:`parent_map` gives the sub-domain→parent-domain gather (every
    host lies in exactly one rack, every rack in exactly one zone) the
    spread kernels segment over.
    """

    keys: TopologyKeys
    missing: str
    host_code: np.ndarray
    rack_code: np.ndarray
    zone_code: np.ndarray
    host_domains: list = field(default_factory=list)
    rack_domains: list = field(default_factory=list)
    zone_domains: list = field(default_factory=list)
    missing_labels: dict = field(default_factory=dict)

    @property
    def n_nodes(self) -> int:
        return int(self.host_code.shape[0])

    def codes(self, level: str) -> np.ndarray:
        self._check_level(level)
        return getattr(self, f"{level}_code")

    def domains(self, level: str) -> list:
        self._check_level(level)
        return getattr(self, f"{level}_domains")

    def n_domains(self, level: str) -> int:
        return len(self.domains(level))

    @staticmethod
    def _check_level(level: str) -> None:
        if level not in LEVELS:
            raise ValueError(
                f"unknown topology level {level!r} (want one of {LEVELS})"
            )

    @property
    def host_singleton(self) -> bool:
        """True iff every host domain holds exactly one node — the
        common unique-hostname fleet, where host-level domain capacity
        IS per-node capacity (the grouped gang fast path's guard)."""
        codes = self.host_code
        member = codes >= 0
        return len(self.host_domains) == int(member.sum())

    def parent_map(self, sub: str, parent: str) -> np.ndarray:
        """``[n_domains(sub)]`` int64: each sub-domain's parent-domain
        code (``-1`` when the sub-domain's nodes are parent-excluded).
        Well-defined because domains nest by construction."""
        if LEVEL_ORDER[sub] >= LEVEL_ORDER[parent]:
            raise ValueError(
                f"{sub!r} is not strictly finer than {parent!r}"
            )
        sub_codes = self.codes(sub)
        parent_codes = self.codes(parent)
        out = np.full(len(self.domains(sub)), -1, dtype=np.int64)
        member = sub_codes >= 0
        out[sub_codes[member]] = parent_codes[member]
        return out


def _nested_codes(labels, key, parent_eff, *, missing, n):
    """Codes for one level, keyed by ``(parent domain, own label)`` so
    equal label values under different parents stay distinct domains."""
    codes = np.full(n, -1, dtype=np.int64)
    domains: list[str] = []
    ids: dict = {}
    missing_count = 0
    for i in range(n):
        row = labels[i] if i < len(labels) else None
        value = (row or {}).get(key)
        if value is None:
            missing_count += 1
            if missing == "own":
                codes[i] = len(domains)
                domains.append(f"~node:{i}")
            continue
        nested = (parent_eff[i], value)
        code = ids.get(nested)
        if code is None:
            code = ids[nested] = len(domains)
            domains.append(value if parent_eff[i] is None
                           else f"{parent_eff[i]}/{value}")
        codes[i] = code
    return codes, domains, missing_count


def topology_from_snapshot(
    snapshot,
    *,
    keys: TopologyKeys | None = None,
    missing: str = "own",
) -> ClusterTopology:
    """Parse the snapshot's labels into a :class:`ClusterTopology`.

    Memoized per ``(keys, missing)`` on the (immutable) snapshot — the
    label walk is O(N) Python and every gang/watch evaluation of one
    generation reuses it.  Array-built snapshots with no labels still
    work: every level falls to the missing policy (``"own"`` makes each
    node a singleton at every level — gang co-location then degenerates
    to per-node arithmetic, explicitly, not wrongly).  A pre-attached
    topology (:func:`attach_topology` — the synthetic 1M-node path)
    short-circuits the walk entirely.
    """
    if missing not in _MISSING_POLICIES:
        raise ValueError(
            f"missing-label policy must be one of {_MISSING_POLICIES}, "
            f"got {missing!r}"
        )
    keys = keys or TopologyKeys()
    cache = snapshot.__dict__.setdefault("_topology_cache", {})
    cache_key = (keys, missing)
    hit = cache.get(cache_key)
    if hit is not None:
        return hit
    n = snapshot.n_nodes
    labels = snapshot.labels or []

    zone_code, zone_domains, zone_missing = label_codes(
        labels, keys.zone, missing=missing, n_nodes=n
    )
    # Effective parent tag per node for nesting (None = no zone and the
    # exclude policy — nested values then group under a shared "no
    # parent" bucket, which the policy already excluded anyway).
    zone_eff = [
        zone_domains[int(c)] if c >= 0 else None for c in zone_code
    ]
    rack_code, rack_domains, rack_missing = _nested_codes(
        labels, keys.rack, zone_eff, missing=missing, n=n
    )
    rack_eff = [
        rack_domains[int(c)] if c >= 0 else None for c in rack_code
    ]
    host_code, host_domains, host_missing = _nested_codes(
        labels, keys.host, rack_eff, missing=missing, n=n
    )
    topo = ClusterTopology(
        keys=keys,
        missing=missing,
        host_code=host_code,
        rack_code=rack_code,
        zone_code=zone_code,
        host_domains=host_domains,
        rack_domains=rack_domains,
        zone_domains=zone_domains,
        missing_labels={
            "host": host_missing,
            "rack": rack_missing,
            "zone": zone_missing,
        },
    )
    cache[cache_key] = topo
    return topo


def attach_topology(
    snapshot,
    zone_code,
    rack_code,
    *,
    keys: TopologyKeys | None = None,
    missing: str = "own",
) -> ClusterTopology:
    """Attach precomputed zone/rack codes to a snapshot (the array-level
    synthetic path: a 1M-node fleet's hierarchy is generated as numpy
    columns, never as 1M label dicts walked back into columns).

    Host codes are the identity (every node its own host — the unique-
    hostname fleet).  Rack codes must already nest (a rack code maps to
    exactly one zone code); violated nesting raises rather than
    producing a silently-wrong hierarchy.  The result lands in the same
    memo :func:`topology_from_snapshot` reads, under the same key.
    """
    n = snapshot.n_nodes
    zone_code = np.asarray(zone_code, dtype=np.int64)
    rack_code = np.asarray(rack_code, dtype=np.int64)
    if zone_code.shape != (n,) or rack_code.shape != (n,):
        raise ValueError(
            f"topology codes must be shape ({n},), got "
            f"{zone_code.shape}/{rack_code.shape}"
        )
    n_zones = int(zone_code.max()) + 1 if n else 0
    n_racks = int(rack_code.max()) + 1 if n else 0
    if n and (zone_code.min() < 0 or rack_code.min() < 0):
        raise ValueError("attached topology codes must be >= 0")
    # Nesting check: each rack code maps to exactly one zone code.
    parent = np.full(n_racks, -1, dtype=np.int64)
    parent[rack_code] = zone_code
    if n and not (parent[rack_code] == zone_code).all():
        raise ValueError(
            "rack codes do not nest inside zone codes (a rack spans "
            "two zones) — build nested codes, the hierarchy is a tree"
        )
    topo = ClusterTopology(
        keys=keys or TopologyKeys(),
        missing=missing,
        host_code=np.arange(n, dtype=np.int64),
        rack_code=rack_code,
        zone_code=zone_code,
        host_domains=list(snapshot.names),
        rack_domains=[f"rack-{r}" for r in range(n_racks)],
        zone_domains=[f"zone-{z}" for z in range(n_zones)],
        missing_labels={"host": 0, "rack": 0, "zone": 0},
    )
    cache = snapshot.__dict__.setdefault("_topology_cache", {})
    cache[(topo.keys, missing)] = topo
    return topo
