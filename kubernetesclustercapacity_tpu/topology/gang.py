"""Gang capacity: whole-gang counting over the topology hierarchy.

A **gang** is ``ranks`` co-scheduled replicas of one per-rank pod spec
(an MPI job, a training step's workers) whose capacity is all-or-
nothing: 63 of 64 ranks is zero gangs.  The reference — and every
framework surface before this PR — counts independent pods; this module
answers "how many WHOLE gangs fit", under the topology constraints
rank-aware schedulers actually enforce:

* **co-location** (``colocate``): every rank of a gang inside one
  domain of a level (``host``/``rack``/``zone``) — gangs may not span
  domains, though one domain may hold several gangs;
* **rank-aware spread** (``spread_level`` + ``max_ranks_per_domain``):
  at most k ranks of any ONE gang per domain of a (finer) level;
* **per-host anti-affinity** (``anti_affinity_host``): sugar for
  ``spread_level="host", max_ranks_per_domain=1``.

The math rides the per-node fit column every other surface uses
(bit-identical to ``fit_per_node``), reduced by topology code:

* co-location: domain capacity ``c_d = clamp(Σ_{n∈d} fit_n)``, gangs
  ``Σ_d c_d // R`` — a segmented sum and a floor-divide, jit-pure,
  vectorized over the scenario axis;
* spread: for each co-domain, the largest G with
  ``Σ_sub min(c_sub, G·k) ≥ G·R``.  That condition is exact — by
  max-flow/min-cut on the gang×domain transportation network the
  min cut is ``Σ_sub min(c_sub, G·k)`` — and the feasible set is an
  interval (``Σ min(c, G·k)`` is concave in G), so a vectorized
  binary search inside one jit program finds G* per (scenario,
  co-domain).

**Grouped 1M-node path**: the (shape, count) compression (PR 9) keeps
working because domain membership folds into per-(group, domain)
COUNT matrices instead of the group key: a group's fit is shape-
determined, so ``Σ_{n∈d} fit_n = Σ_g cnt[g,d]·fit_g`` exactly, and the
whole gang reduction is an ``[S,G]×[G,D]`` matmul over ~100s of groups
— compression is never sacrificed to topology.  Host-level constraints
use the singleton-host identity (``c_host = fit_node`` on unique-
hostname fleets); fleets with shared host domains fall back to the
per-node path, explicitly.  ``KCCAP_GANG_GROUPED=0`` forces the
per-node reduction (the escape hatch, mirroring ``KCCAP_GROUPING``).

Domain capacities clamp into ``[0, 2^40]`` ranks before the gang
arithmetic — negative (reference-mode phantom/overcommit) capacity
holds no ranks, and beyond a trillion ranks the count saturates rather
than risking int64 wrap inside the search.  The pure numpy/Python
oracle (:func:`gang_oracle`) applies the identical clamp, so parity is
exact by construction.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field, replace
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from kubernetesclustercapacity_tpu.topology.model import (
    LEVEL_ORDER,
    LEVELS,
    ClusterTopology,
    TopologyKeys,
    topology_from_snapshot,
)

__all__ = [
    "GangSpec",
    "GangSpecError",
    "GangResult",
    "gang_capacity",
    "gang_explain",
    "gang_oracle",
    "gang_spec_from_msg",
    "load_gang_spec",
    "parse_gang_block",
    "gang_grouped_enabled",
]

#: Carrier-safety clamp on domain capacities (ranks): negative holds
#: nothing, and past ~10^12 the gang count saturates instead of letting
#: ``G·k`` / ``G·R`` products wrap the int64 carrier mid-search.
CAP_MAX = 1 << 40


def gang_grouped_enabled() -> bool:
    """``KCCAP_GANG_GROUPED=0`` forces the per-node gang reduction even
    when grouped dispatch engages — the same restart-free escape hatch
    policy as ``KCCAP_GROUPING``, scoped to the gang kernels."""
    return os.environ.get("KCCAP_GANG_GROUPED", "1") != "0"


class GangSpecError(ValueError):
    """Malformed gang spec — every constraint-field inconsistency is a
    typed rejection with a clear message, never a silently-unconstrained
    evaluation (the ``place_replicas`` spread-knob guard's policy)."""


@dataclass(frozen=True)
class GangSpec:
    """R ranks of one per-rank pod plus the topology constraints.

    ``count`` is the schedulability target in WHOLE GANGS (the gang
    analog of replicas: ``schedulable = gangs >= count``).  Constraint
    fields and their validation are the module docstring's vocabulary.
    """

    ranks: int
    count: int = 1
    colocate: str | None = None
    spread_level: str | None = None
    max_ranks_per_domain: int | None = None
    anti_affinity_host: bool = False

    def __post_init__(self) -> None:
        if not isinstance(self.ranks, int) or isinstance(self.ranks, bool):
            raise GangSpecError(f"ranks must be an integer, got {self.ranks!r}")
        if self.ranks < 1:
            raise GangSpecError(f"ranks must be >= 1, got {self.ranks}")
        if not isinstance(self.count, int) or isinstance(self.count, bool):
            raise GangSpecError(f"count must be an integer, got {self.count!r}")
        if self.count < 0:
            raise GangSpecError(f"count must be >= 0, got {self.count}")
        for name in ("colocate", "spread_level"):
            lvl = getattr(self, name)
            if lvl is not None and lvl not in LEVELS:
                raise GangSpecError(
                    f"{name} must be one of {LEVELS}, got {lvl!r}"
                )
        # The place_replicas guard, gang-flavored: a cap without the
        # level it applies to (or a level without a cap) would evaluate
        # silently unconstrained — reject, never guess.
        if (self.max_ranks_per_domain is None) != (self.spread_level is None):
            raise GangSpecError(
                "max_ranks_per_domain and spread_level go together — a "
                "cap without its level (or a level without a cap) would "
                "leave the gang silently unconstrained"
            )
        if self.max_ranks_per_domain is not None:
            if not isinstance(self.max_ranks_per_domain, int) or isinstance(
                self.max_ranks_per_domain, bool
            ):
                raise GangSpecError(
                    f"max_ranks_per_domain must be an integer, got "
                    f"{self.max_ranks_per_domain!r}"
                )
            if self.max_ranks_per_domain < 1:
                raise GangSpecError(
                    f"max_ranks_per_domain must be >= 1, got "
                    f"{self.max_ranks_per_domain}"
                )
        if self.colocate is not None and self.spread_level is not None:
            if LEVEL_ORDER[self.spread_level] >= LEVEL_ORDER[self.colocate]:
                raise GangSpecError(
                    f"spread_level {self.spread_level!r} must be strictly "
                    f"finer than colocate {self.colocate!r} (hierarchy: "
                    f"{' < '.join(LEVELS)})"
                )
        if not isinstance(self.anti_affinity_host, bool):
            raise GangSpecError(
                f"anti_affinity_host must be a bool, got "
                f"{self.anti_affinity_host!r}"
            )
        if self.anti_affinity_host and self.spread_level == "host":
            raise GangSpecError(
                "anti_affinity_host IS a host-level spread cap of 1 — "
                "give one host constraint, not two"
            )
        if self.anti_affinity_host and self.colocate == "host":
            raise GangSpecError(
                "anti_affinity_host (one rank per host) contradicts "
                "colocate='host' (all ranks on one host)"
            )

    def effective_spread(self) -> tuple[str, int] | None:
        """The one spread constraint in force: ``(level, cap)`` or
        ``None``.  ``anti_affinity_host`` desugars to ``("host", 1)``;
        a cap above ``ranks`` is vacuous and clamps to ``ranks`` (a
        gang has only R ranks to place)."""
        if self.anti_affinity_host:
            return ("host", 1)
        if self.spread_level is not None:
            return (self.spread_level, min(self.max_ranks_per_domain, self.ranks))
        return None

    def to_wire(self) -> dict:
        out: dict = {"ranks": self.ranks, "count": self.count}
        if self.colocate is not None:
            out["colocate"] = self.colocate
        if self.spread_level is not None:
            out["spread_level"] = self.spread_level
            out["max_ranks_per_domain"] = self.max_ranks_per_domain
        if self.anti_affinity_host:
            out["anti_affinity_host"] = True
        return out


_GANG_KEYS = frozenset(
    {
        "ranks", "count", "colocate", "spread_level",
        "max_ranks_per_domain", "anti_affinity_host",
    }
)


def parse_gang_block(block) -> GangSpec:
    """A watchlist/wire ``gang:`` mapping → :class:`GangSpec` (unknown
    keys rejected — a typo'd constraint must never evaluate as
    unconstrained)."""
    if not isinstance(block, dict):
        raise GangSpecError(f"gang block must be a mapping, got {block!r}")
    unknown = set(block) - _GANG_KEYS
    if unknown:
        raise GangSpecError(
            f"unknown gang field(s) {sorted(unknown)} "
            f"(want {sorted(_GANG_KEYS)})"
        )
    if "ranks" not in block:
        raise GangSpecError("gang block needs 'ranks'")
    return GangSpec(
        ranks=block["ranks"],
        count=block.get("count", 1),
        colocate=block.get("colocate"),
        spread_level=block.get("spread_level"),
        max_ranks_per_domain=block.get("max_ranks_per_domain"),
        anti_affinity_host=block.get("anti_affinity_host", False),
    )


def gang_spec_from_msg(msg: dict) -> GangSpec:
    """The wire form: gang fields ride the request envelope flat (the
    protocol's flag convention), with string integers accepted."""

    def as_int(name, default=None):
        v = msg.get(name, default)
        if v is None or isinstance(v, bool):
            return v if v is None else v
        try:
            return int(v)
        except (TypeError, ValueError):
            raise GangSpecError(f"{name} must be an integer, got {v!r}")

    return GangSpec(
        ranks=as_int("ranks"),
        count=as_int("count", 1),
        colocate=msg.get("colocate"),
        spread_level=msg.get("spread_level"),
        max_ranks_per_domain=as_int("max_ranks_per_domain"),
        anti_affinity_host=bool(msg.get("anti_affinity_host", False)),
    )


def load_gang_spec(path: str):
    """``kccap -gang-spec FILE``: the watchlist grammar's pod block plus
    a ``gang:`` block in one document.  Returns ``(scenario, GangSpec)``.

    YAML when PyYAML is present, strict JSON otherwise — the same
    loader policy as the watchlist's.
    """
    import json as _json

    from kubernetesclustercapacity_tpu.scenario import (
        ScenarioError,
        scenario_from_flags,
    )

    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    try:
        import yaml  # type: ignore[import-untyped]

        data = yaml.safe_load(text)
    except ImportError:
        try:
            data = _json.loads(text)
        except ValueError as e:
            raise GangSpecError(
                f"{path}: not valid JSON (and PyYAML is unavailable): {e}"
            ) from e
    except Exception as e:  # yaml.YAMLError — malformed document
        raise GangSpecError(f"{path}: cannot parse: {e}") from e
    if not isinstance(data, dict):
        raise GangSpecError(f"{path}: gang spec wants a mapping document")
    extra = set(data) - {"pod", "gang"}
    if extra:
        raise GangSpecError(
            f"{path}: unknown top-level field(s) {sorted(extra)} "
            "(want pod/gang)"
        )
    pod = data.get("pod") or {}
    if not isinstance(pod, dict):
        raise GangSpecError(f"{path}: 'pod' must be a mapping")
    try:
        scenario = scenario_from_flags(**{k: str(v) for k, v in pod.items()})
        scenario.validate()
    except (TypeError, ScenarioError) as e:
        raise GangSpecError(f"{path}: bad pod spec: {e}") from e
    if "gang" not in data:
        raise GangSpecError(f"{path}: gang spec needs a 'gang' block")
    return scenario, parse_gang_block(data["gang"])


# --- jit kernels --------------------------------------------------------


@partial(jax.jit, static_argnames=("n_domains",))
def _domain_caps(fits_sn, codes, *, n_domains: int):
    """``[S, N]`` fits × ``[N]`` codes → clamped ``[S, D]`` domain
    capacities.  One segmented sum per scenario row (code ``-1`` spills
    into a discarded slot), then the carrier-safety clamp."""
    fits = jnp.asarray(fits_sn, jnp.int64)
    codes = jnp.asarray(codes, jnp.int64)
    ok = codes >= 0
    seg = jnp.where(ok, codes, n_domains)

    def one(row):
        return jax.ops.segment_sum(
            jnp.where(ok, row, 0), seg, num_segments=n_domains + 1
        )[:n_domains]

    sums = jax.vmap(one)(fits)
    return jnp.clip(sums, 0, CAP_MAX)


@jax.jit
def _grouped_caps(fits_sg, cnt_gd):
    """Grouped form of :func:`_domain_caps`: ``Σ_g cnt[g,d]·fit_g`` via
    an ``[S,G]×[G,D]`` matmul, then the same clamp — exact because a
    group's fit is every member's fit."""
    sums = jnp.asarray(fits_sg, jnp.int64) @ jnp.asarray(cnt_gd, jnp.int64)
    return jnp.clip(sums, 0, CAP_MAX)


@jax.jit
def _gangs_colocated(caps_sd, ranks):
    """Whole gangs under co-location: ``Σ_d c_d // R`` per scenario."""
    caps = jnp.asarray(caps_sd, jnp.int64)
    r = jnp.maximum(jnp.asarray(ranks, jnp.int64), 1)
    return jnp.sum(caps // r, axis=-1)


@jax.jit
def _gangs_colocated_per_group(fits_sg, cnt_g, ranks):
    """Host co-location on a singleton-host grouped fleet: every host's
    capacity IS its node's fit, so gangs = ``Σ_g cnt_g·(clamp(fit_g)//R)``
    — the whole-gang floor-divide stays count-weighted per group."""
    fits = jnp.clip(jnp.asarray(fits_sg, jnp.int64), 0, CAP_MAX)
    r = jnp.maximum(jnp.asarray(ranks, jnp.int64), 1)
    return jnp.sum((fits // r) * jnp.asarray(cnt_g, jnp.int64)[None, :], axis=-1)


@partial(jax.jit, static_argnames=("n_co",))
def _gangs_spread(sub_caps_sd, parent_d, ranks, cap_k, *, n_co: int):
    """Max whole gangs per co-domain under a per-sub-domain rank cap.

    Binary search on G per (scenario, co-domain): feasibility of G gangs
    is ``Σ_{sub∈d} min(c_sub, G·k) ≥ G·R`` (exact by min-cut; the
    feasible set is an interval by concavity), evaluated as one
    segmented sum per search step.  Returns gangs summed over
    co-domains, ``[S]``.
    """
    caps = jnp.asarray(sub_caps_sd, jnp.int64)  # [S, Dsub], pre-clamped
    parent = jnp.asarray(parent_d, jnp.int64)
    ok = parent >= 0
    seg = jnp.where(ok, parent, n_co)
    r = jnp.maximum(jnp.asarray(ranks, jnp.int64), 1)
    k = jnp.maximum(jnp.asarray(cap_k, jnp.int64), 1)

    def seg_sum(x_sd):
        def one(row):
            return jax.ops.segment_sum(
                jnp.where(ok, row, 0), seg, num_segments=n_co + 1
            )[:n_co]

        return jax.vmap(one)(x_sd)

    hi0 = seg_sum(caps) // r  # [S, n_co] upper bound
    lo0 = jnp.zeros_like(hi0)
    safe_parent = jnp.where(ok, parent, 0)

    def cond(state):
        lo, hi = state
        return jnp.any(lo < hi)

    def body(state):
        lo, hi = state
        mid = (lo + hi + 1) // 2
        lim = jnp.take(mid, safe_parent, axis=1) * k  # [S, Dsub]
        supply = seg_sum(jnp.minimum(caps, lim))
        feasible = supply >= mid * r
        return jnp.where(feasible, mid, lo), jnp.where(feasible, hi, mid - 1)

    lo, _ = jax.lax.while_loop(cond, body, (lo0, hi0))
    return jnp.sum(lo, axis=-1)


@jax.jit
def _gangs_spread_per_group(fits_sg, cnt_gd, ranks, cap_k):
    """The spread search on a singleton-host grouped fleet: host caps
    are per-node fits, so the feasibility sum is
    ``Σ_g cnt[g,d]·min(clamp(fit_g), G_d·k)`` — an einsum per search
    step over ~100s of groups × co-domains, never 1M rows."""
    fits = jnp.clip(jnp.asarray(fits_sg, jnp.int64), 0, CAP_MAX)  # [S, G]
    cnt = jnp.asarray(cnt_gd, jnp.int64)  # [G, D]
    r = jnp.maximum(jnp.asarray(ranks, jnp.int64), 1)
    k = jnp.maximum(jnp.asarray(cap_k, jnp.int64), 1)
    hi0 = (fits @ cnt) // r  # [S, D]
    lo0 = jnp.zeros_like(hi0)

    def cond(state):
        lo, hi = state
        return jnp.any(lo < hi)

    def body(state):
        lo, hi = state
        mid = (lo + hi + 1) // 2
        minned = jnp.minimum(fits[:, :, None], mid[:, None, :] * k)  # [S,G,D]
        supply = jnp.einsum("sgd,gd->sd", minned, cnt)
        feasible = supply >= mid * r
        return jnp.where(feasible, mid, lo), jnp.where(feasible, hi, mid - 1)

    lo, _ = jax.lax.while_loop(cond, body, (lo0, hi0))
    return jnp.sum(lo, axis=-1)


# --- host-side assembly -------------------------------------------------


@dataclass
class GangResult:
    """Gang capacity of S scenarios (numpy throughout).

    ``gangs[s]`` whole gangs; ``schedulable[s] = gangs >= spec.count``;
    ``pod_totals[s]`` the plain (gang-free) pod capacity for contrast;
    ``largest_cap``/``largest_domain`` the biggest co-location domain's
    rank capacity and name per scenario (cluster-wide when
    ``colocate`` is None); ``engine`` which reduction served
    (``"grouped"`` count-matrix or ``"per-node"``).
    """

    spec: GangSpec
    gangs: np.ndarray
    pod_totals: np.ndarray
    largest_cap: np.ndarray
    largest_domain: list
    mode: str
    engine: str
    excluded_nodes: int = 0
    co_caps: np.ndarray | None = field(default=None, repr=False)
    co_domains: list = field(default_factory=list, repr=False)

    @property
    def schedulable(self) -> np.ndarray:
        return self.gangs >= np.int64(self.spec.count)

    @property
    def size(self) -> int:
        return int(self.gangs.shape[0])

    def to_wire(self) -> dict:
        out = {
            "gangs": [int(g) for g in self.gangs],
            "schedulable": [bool(b) for b in self.schedulable],
            "pod_totals": [int(t) for t in self.pod_totals],
            "scenarios": self.size,
            "mode": self.mode,
            "engine": self.engine,
            "excluded_nodes": self.excluded_nodes,
            **self.spec.to_wire(),
        }
        return out


def _contingency(group_index, codes, n_groups, n_domains, node_mask):
    """``cnt[g, d]`` — nodes of shape group g inside domain d (masked
    and code-excluded nodes drop out), as one flat bincount."""
    keep = codes >= 0
    if node_mask is not None:
        keep = keep & np.asarray(node_mask, dtype=bool)
    flat = group_index[keep] * n_domains + codes[keep]
    return np.bincount(flat, minlength=n_groups * n_domains).astype(
        np.int64
    ).reshape(n_groups, n_domains)


def _level_codes(topo: ClusterTopology, level: str | None):
    """Codes and domain names at one level; ``None`` = the single
    cluster-wide domain."""
    if level is None:
        return np.zeros(topo.n_nodes, dtype=np.int64), ["cluster"]
    return topo.codes(level), topo.domains(level)


def _grouped_eligible(spec: GangSpec, topo: ClusterTopology) -> bool:
    """The grouped count-matrix path needs host-level constraints to
    mean per-node constraints (singleton hosts); rack/zone levels are
    always eligible (count matrices are exact at any compression)."""
    spread = spec.effective_spread()
    needs_host = spec.colocate == "host" or (
        spread is not None and spread[0] == "host"
    )
    return not needs_host or topo.host_singleton


def gang_capacity(
    snapshot,
    grid,
    spec: GangSpec,
    *,
    mode: str | None = None,
    node_mask=None,
    keys: TopologyKeys | None = None,
    missing: str = "own",
    topology: ClusterTopology | None = None,
) -> GangResult:
    """Whole-gang capacity of every scenario in ``grid`` under ``spec``.

    Per-rank fits come from the production kernel path (grouped /
    bucketed / devcached exactly as the env gates say), then reduce
    through the topology codes per the module's semantics.  ``mode``
    defaults to the snapshot's packing semantics and ``node_mask``
    composes like every fit surface (a masked node holds no ranks).
    Bit-exact against :func:`gang_oracle` in both semantics modes and
    across the grouped/ungrouped × bucketed/unbucketed dispatch matrix.
    """
    from kubernetesclustercapacity_tpu.ops.fit import (
        sweep_grid_grouped,
        sweep_snapshot,
    )
    from kubernetesclustercapacity_tpu.snapshot import grouped_for_dispatch

    mode = mode or snapshot.semantics
    grid.validate()
    topo = topology or topology_from_snapshot(
        snapshot, keys=keys, missing=missing
    )
    spread = spec.effective_spread()
    grouped = (
        grouped_for_dispatch(snapshot) if gang_grouped_enabled() else None
    )
    if grouped is not None and not _grouped_eligible(spec, topo):
        grouped = None

    co_codes, co_domains = _level_codes(topo, spec.colocate)
    excluded = int((co_codes < 0).sum())
    if spread is not None:
        sub_codes, _sub_domains = _level_codes(topo, spread[0])
        excluded = max(excluded, int((sub_codes < 0).sum()))

    if grouped is not None:
        fits_g = np.asarray(
            sweep_grid_grouped(
                grouped.alloc_cpu_milli,
                grouped.alloc_mem_bytes,
                grouped.alloc_pods,
                grouped.used_cpu_req_milli,
                grouped.used_mem_req_bytes,
                grouped.pods_count,
                grouped.healthy,
                grouped.count,
                grid.cpu_request_milli,
                grid.mem_request_bytes,
                grid.replicas,
                mode=mode,
                return_per_group=True,
            )[2]
        )  # [S, G]
        counts = grouped.effective_counts(node_mask)
        pod_totals = fits_g @ counts
        g_idx, n_g = grouped.group_index, grouped.n_groups
        cnt_co = _contingency(
            g_idx, co_codes, n_g, len(co_domains), node_mask
        )
        if spec.colocate == "host":
            # Singleton hosts (eligibility-guarded): per-group closed form.
            cnt_g = cnt_co.sum(axis=1)
            gangs = np.asarray(
                _gangs_colocated_per_group(fits_g, cnt_g, spec.ranks)
            )
            co_caps = None
            largest_cap, largest_domain = _largest_group_host(
                fits_g, cnt_g, grouped
            )
        elif spread is not None and spread[0] == "host":
            gangs = np.asarray(
                _gangs_spread_per_group(
                    fits_g, cnt_co, spec.ranks, spread[1]
                )
            )
            co_caps = np.asarray(_grouped_caps(fits_g, cnt_co))
            largest_cap, largest_domain = _largest_of(co_caps, co_domains)
        elif spread is not None:
            cnt_sub = _contingency(
                g_idx, sub_codes, n_g, len(_sub_domains), node_mask
            )
            sub_caps = np.asarray(_grouped_caps(fits_g, cnt_sub))
            parent = (
                topo.parent_map(spread[0], spec.colocate)
                if spec.colocate is not None
                else np.zeros(len(_sub_domains), dtype=np.int64)
            )
            gangs = np.asarray(
                _gangs_spread(
                    sub_caps, parent, spec.ranks, spread[1],
                    n_co=len(co_domains),
                )
            )
            co_caps = np.asarray(_grouped_caps(fits_g, cnt_co))
            largest_cap, largest_domain = _largest_of(co_caps, co_domains)
        else:
            co_caps = np.asarray(_grouped_caps(fits_g, cnt_co))
            gangs = np.asarray(_gangs_colocated(co_caps, spec.ranks))
            largest_cap, largest_domain = _largest_of(co_caps, co_domains)
        engine = "grouped"
    else:
        fits = np.asarray(
            sweep_snapshot(
                snapshot, grid, mode=mode,
                return_per_node=True, node_mask=node_mask,
            )[2]
        )  # [S, N]
        pod_totals = fits.sum(axis=1)
        masked_codes = _masked(co_codes, node_mask)
        co_caps = np.asarray(
            _domain_caps(fits, masked_codes, n_domains=len(co_domains))
        )
        if spread is None:
            gangs = np.asarray(_gangs_colocated(co_caps, spec.ranks))
        else:
            sub_masked = _masked(sub_codes, node_mask)
            sub_caps = np.asarray(
                _domain_caps(fits, sub_masked, n_domains=len(_sub_domains))
            )
            parent = (
                topo.parent_map(spread[0], spec.colocate)
                if spec.colocate is not None
                else np.zeros(len(_sub_domains), dtype=np.int64)
            )
            gangs = np.asarray(
                _gangs_spread(
                    sub_caps, parent, spec.ranks, spread[1],
                    n_co=len(co_domains),
                )
            )
        largest_cap, largest_domain = _largest_of(co_caps, co_domains)
        engine = "per-node"

    return GangResult(
        spec=spec,
        gangs=np.asarray(gangs, dtype=np.int64),
        pod_totals=np.asarray(pod_totals, dtype=np.int64),
        largest_cap=largest_cap,
        largest_domain=largest_domain,
        mode=mode,
        engine=engine,
        excluded_nodes=excluded,
        co_caps=co_caps,
        co_domains=list(co_domains),
    )


def _masked(codes: np.ndarray, node_mask) -> np.ndarray:
    """Fold the node mask into the code column (masked row → code -1 →
    contributes to no domain)."""
    if node_mask is None:
        return codes
    return np.where(np.asarray(node_mask, dtype=bool), codes, -1)


def _largest_of(caps_sd: np.ndarray, domains: list):
    """Per-scenario biggest co-domain: (cap, name)."""
    if caps_sd.shape[1] == 0:
        s = caps_sd.shape[0]
        return np.zeros(s, dtype=np.int64), [None] * s
    arg = np.argmax(caps_sd, axis=1)
    return (
        caps_sd[np.arange(caps_sd.shape[0]), arg].astype(np.int64),
        [domains[int(a)] for a in arg],
    )


def _largest_group_host(fits_sg, cnt_g, grouped):
    """Biggest host (= node) per scenario on the grouped path: the max
    clamped per-group fit among populated groups, named by the group's
    representative node."""
    fits = np.clip(np.asarray(fits_sg, dtype=np.int64), 0, CAP_MAX)
    populated = cnt_g > 0
    if not populated.any():
        s = fits.shape[0]
        return np.zeros(s, dtype=np.int64), [None] * s
    masked = np.where(populated[None, :], fits, -1)
    arg = np.argmax(masked, axis=1)
    names = grouped.representative_names()
    return (
        np.maximum(masked[np.arange(fits.shape[0]), arg], 0),
        [names[int(a)] for a in arg],
    )


# --- oracle -------------------------------------------------------------


def _oracle_caps(fits_n, codes, n_domains) -> np.ndarray:
    caps = np.zeros(n_domains + 1, dtype=np.int64)
    safe = np.where(codes >= 0, codes, n_domains)
    np.add.at(caps, safe, np.asarray(fits_n, dtype=np.int64))
    return np.clip(caps[:n_domains], 0, CAP_MAX)


def _oracle_spread_count(sub_caps: np.ndarray, ranks: int, k: int) -> int:
    """Largest G with ``Σ min(c, G·k) >= G·R`` — Python bisection over
    the same concave feasibility the kernel searches (an independent
    implementation, not a shared one)."""
    k = min(k, ranks)
    lo, hi = 0, int(sub_caps.sum()) // max(ranks, 1)

    def feasible(g: int) -> bool:
        return int(np.minimum(sub_caps, g * k).sum()) >= g * ranks

    while lo < hi:
        mid = (lo + hi + 1) // 2
        if feasible(mid):
            lo = mid
        else:
            hi = mid - 1
    return lo


def gang_oracle(
    fits_sn, topo: ClusterTopology, spec: GangSpec, *, node_mask=None
) -> list[int]:
    """Pure numpy/Python gang counting over per-node fits — the ground
    truth the kernels pin against (no JAX anywhere on this path)."""
    fits = np.asarray(fits_sn, dtype=np.int64)
    if fits.ndim == 1:
        fits = fits[None, :]
    co_codes, co_domains = _level_codes(topo, spec.colocate)
    co_codes = _masked(co_codes, node_mask)
    spread = spec.effective_spread()
    out: list[int] = []
    for s in range(fits.shape[0]):
        if spread is None:
            caps = _oracle_caps(fits[s], co_codes, len(co_domains))
            out.append(int(sum(int(c) // spec.ranks for c in caps)))
            continue
        sub_codes, sub_domains = _level_codes(topo, spread[0])
        sub_codes = _masked(sub_codes, node_mask)
        sub_caps = _oracle_caps(fits[s], sub_codes, len(sub_domains))
        parent = (
            topo.parent_map(spread[0], spec.colocate)
            if spec.colocate is not None
            else np.zeros(len(sub_domains), dtype=np.int64)
        )
        total = 0
        for d in range(len(co_domains)):
            subs = sub_caps[parent == d]
            if subs.size:
                total += _oracle_spread_count(subs, spec.ranks, spread[1])
        out.append(total)
    return out


# --- explain ------------------------------------------------------------


def gang_explain(
    snapshot,
    grid,
    spec: GangSpec,
    *,
    mode: str | None = None,
    node_mask=None,
    keys: TopologyKeys | None = None,
    missing: str = "own",
    scenario: int = 0,
) -> dict:
    """WHY the gang count stops where it does: which topology LEVEL
    binds, contrasted with the cluster-wide resource story.

    Evaluates the spec, then re-evaluates with each constraint peeled
    (spread dropped; co-location dropped) to attribute the loss: the
    binding level is the finest constraint whose removal would raise
    the count; ``"cluster"`` means topology is not the constraint —
    plain resource headroom is, named via the pod-level explain
    histogram.  Verified against brute-force per-domain enumeration in
    ``tests/test_topology_gang.py``.
    """
    from kubernetesclustercapacity_tpu.explain import explain_snapshot

    mode = mode or snapshot.semantics
    topo = topology_from_snapshot(snapshot, keys=keys, missing=missing)
    result = gang_capacity(
        snapshot, grid, spec, mode=mode, node_mask=node_mask, topology=topo
    )
    s = scenario
    gangs = int(result.gangs[s])
    pod_total = int(result.pod_totals[s])
    cluster_gangs = int(min(max(pod_total, 0), CAP_MAX)) // spec.ranks
    spread = spec.effective_spread()

    no_spread = gangs
    if spread is not None:
        bare = replace(
            spec,
            spread_level=None,
            max_ranks_per_domain=None,
            anti_affinity_host=False,
        )
        no_spread = int(
            gang_capacity(
                snapshot, grid, bare, mode=mode, node_mask=node_mask,
                topology=topo,
            ).gangs[s]
        )

    if spread is not None and gangs < no_spread:
        binding = spread[0]
    elif spec.colocate is not None and gangs < cluster_gangs:
        binding = spec.colocate
    else:
        binding = "cluster"

    ex = explain_snapshot(
        snapshot, _one_scenario(grid, s), mode=mode, node_mask=node_mask
    )
    counts = ex.binding_counts(0)
    resource = max(
        ("cpu", "memory", "pods"), key=lambda r: counts.get(r, 0)
    )
    largest = {
        "name": result.largest_domain[s],
        "capacity": int(result.largest_cap[s]),
        "whole_gangs": int(result.largest_cap[s]) // spec.ranks,
    }
    level_word = spec.colocate or "cluster"
    if binding == "cluster":
        summary = (
            f"binds at cluster: {resource} headroom caps "
            f"{gangs} whole gang(s) of {spec.ranks}"
        )
    elif binding == spec.colocate:
        summary = (
            f"binds at {binding}: largest {binding} holds "
            f"{largest['capacity']}/{spec.ranks} ranks; cluster-wide "
            f"{resource} headroom is not the constraint"
        )
    else:
        summary = (
            f"binds at {binding}: max {spread[1]} rank(s) per {binding} "
            f"caps gangs at {gangs} (unconstrained {level_word} gangs: "
            f"{no_spread}); cluster-wide {resource} headroom is not "
            "the constraint"
        )
    out = {
        "gangs": gangs,
        "schedulable": bool(result.schedulable[s]),
        "binding": binding,
        "cluster_pods": pod_total,
        "cluster_gangs": cluster_gangs,
        "largest_domain": largest,
        "binding_counts": counts,
        "excluded_nodes": result.excluded_nodes,
        "summary": summary,
        **spec.to_wire(),
    }
    if spread is not None:
        out["gangs_without_spread"] = no_spread
    return out


def _one_scenario(grid, s: int):
    from kubernetesclustercapacity_tpu.scenario import ScenarioGrid

    return ScenarioGrid(
        cpu_request_milli=np.asarray(grid.cpu_request_milli)[[s]],
        mem_request_bytes=np.asarray(grid.mem_request_bytes)[[s]],
        replicas=np.asarray(grid.replicas)[[s]],
    )
