"""Device-resident snapshot cache + the shape-bucket ladder (hot path).

Every ``fit``/``sweep`` request used to re-upload the snapshot's seven
node arrays host→device (``jnp.asarray`` inside the dispatch) and to
compile a fresh executable whenever the node count changed by one.  The
per-request *work* is tiny; the per-request *overhead* was the product —
the same observation the inference-serving world made about KV caches
and shape buckets.  This module is both fixes in one place:

* **Device cache** — :class:`DeviceCache` holds already-``device_put``
  node arrays keyed by ``(snapshot, form, shape-bucket)``.  Snapshots
  are immutable by contract (the packers build them once; the server
  swaps whole objects on reload/update), so identity is the cache key:
  a per-snapshot token is lazily attached and entries die with LRU
  eviction or an explicit :meth:`DeviceCache.invalidate` on snapshot
  swap.  ``KCCAP_DEVCACHE=0`` disables caching AND bucketing — the
  escape hatch restores the exact pre-cache dispatch.
* **Bucket ladder** — :func:`node_bucket` pads the node axis up a small
  geometric ladder (next power of two above a configurable floor), and
  :func:`scenario_bucket` does the same for the scenario axis.  Zero
  node rows are fit-neutral in both semantics modes (proven in
  ``parallel/sweep.py``: ``alloc <= used`` guards to 0, then the Q1 cap
  rewrites ``0 >= 0`` to ``0 - 0``), and padded scenarios are harmless
  ``(1 milli, 1 byte)`` probes whose outputs are sliced off — so a
  cluster growing 1000 → 1001 nodes reuses the 1024-bucket executable
  instead of recompiling.

Cache hit/miss counters land on the process telemetry registry
(``kccap_devcache_*``); ``doctor``, the service ``info`` op and
``bench.py`` all read :meth:`DeviceCache.stats`.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict

import numpy as np

__all__ = [
    "DeviceCache",
    "CACHE",
    "enabled",
    "node_bucket",
    "scenario_bucket",
    "node_bucket_floor",
    "set_node_bucket_floor",
]

#: Default floor of the node-axis bucket ladder.  Below the floor every
#: cluster shares one executable; above it buckets double, so a snapshot
#: sees at most ``log2(N/floor)`` distinct compiled shapes over its life.
DEFAULT_NODE_BUCKET_FLOOR = 256

#: Scenario-axis floor: grids are usually small and request-shaped, so a
#: low floor keeps padding waste bounded while collapsing the long tail
#: of distinct S values onto a handful of executables.
SCENARIO_BUCKET_FLOOR = 16

_floor_lock = threading.Lock()
_node_floor: int | None = None


def enabled() -> bool:
    """Process-wide hot-path switch (``KCCAP_DEVCACHE=0`` disables).

    Checked per dispatch so the escape hatch works without a restart;
    off means no caching *and* no shape bucketing — byte-for-byte the
    pre-cache dispatch behavior.
    """
    return os.environ.get("KCCAP_DEVCACHE", "1") != "0"


def node_bucket_floor() -> int:
    """The active node-bucket floor (flag/env-configurable)."""
    global _node_floor
    with _floor_lock:
        if _node_floor is None:
            try:
                env = int(os.environ.get("KCCAP_NODE_BUCKET_FLOOR", "0"))
            except ValueError:
                env = 0
            _node_floor = env if env > 0 else DEFAULT_NODE_BUCKET_FLOOR
        return _node_floor


def set_node_bucket_floor(floor: int) -> None:
    """Set the node-bucket floor (``kccap-server -node-bucket-floor``)."""
    global _node_floor
    if floor < 1:
        raise ValueError("node bucket floor must be >= 1")
    with _floor_lock:
        _node_floor = int(floor)


def _next_pow2_at_least(n: int, floor: int) -> int:
    b = max(int(floor), 1)
    n = max(int(n), 1)
    while b < n:
        b <<= 1
    return b


def node_bucket(n: int, floor: int | None = None) -> int:
    """Node axis padded size: next power of two ``>= max(n, floor)``."""
    return _next_pow2_at_least(n, node_bucket_floor() if floor is None else floor)


def scenario_bucket(s: int) -> int:
    """Scenario axis padded size (fixed low floor, then powers of two)."""
    return _next_pow2_at_least(s, SCENARIO_BUCKET_FLOOR)


# Lazily-built telemetry handles on the process registry (importing this
# module must register nothing; KCCAP_TELEMETRY=0 means zero registry
# calls on the hot path — same policy as ops/pallas_fit).
_MET: dict | None = None
_met_lock = threading.Lock()


def _metrics() -> dict:
    global _MET
    if _MET is None:
        with _met_lock:
            if _MET is None:
                from kubernetesclustercapacity_tpu.telemetry.metrics import (
                    REGISTRY,
                )

                _MET = {
                    "hits": REGISTRY.counter(
                        "kccap_devcache_hits_total",
                        "Device-cache hits, by staged form.",
                        ("form",),
                    ),
                    "misses": REGISTRY.counter(
                        "kccap_devcache_misses_total",
                        "Device-cache misses (staged fresh), by form.",
                        ("form",),
                    ),
                }
    return _MET


def _telemetry_enabled() -> bool:
    from kubernetesclustercapacity_tpu.telemetry.metrics import enabled as en

    return en()


class DeviceCache:
    """Thread-safe LRU of device-staged node arrays, keyed per snapshot.

    Generic storage: :meth:`get` takes any hashable ``key`` (its first
    element names the *form* for the hit/miss counters) and a zero-arg
    ``build`` callable.  The exact-kernel and fused-kernel forms have
    dedicated helpers below; the GSPMD path stages through :meth:`get`
    directly with its mesh in the key.

    Keys are scoped by a token lazily attached to the snapshot object —
    snapshots are immutable by contract, so object identity IS content
    identity.  ``max_entries`` bounds device memory: each entry is
    O(bucket) per array, and a serving process holds at most the current
    and the about-to-be-published generation.
    """

    def __init__(self, max_entries: int = 8) -> None:
        self._lock = threading.Lock()
        self._entries: OrderedDict[tuple, object] = OrderedDict()
        self._max_entries = max(1, int(max_entries))
        self._hits = 0
        self._misses = 0
        self._next_token = 0

    def _token(self, snapshot) -> int:
        tok = snapshot.__dict__.get("_devcache_token")
        if tok is None:
            with self._lock:
                tok = snapshot.__dict__.get("_devcache_token")
                if tok is None:
                    tok = self._next_token
                    self._next_token += 1
                    snapshot.__dict__["_devcache_token"] = tok
        return tok

    def get(self, snapshot, key: tuple, build):
        """The cached value for ``(snapshot, key)``; built once.

        ``build`` runs OUTSIDE the lock (it does host padding + a device
        transfer); a concurrent miss may build twice — last store wins,
        both values are equal by construction.
        """
        if not enabled():
            from kubernetesclustercapacity_tpu.telemetry import (
                phases as _phases,
            )

            clk = _phases.current()
            if clk:
                # Cache disabled: every request re-stages — still the
                # devcache phase (the decomposition must show what the
                # escape hatch costs).
                t0 = time.perf_counter()
                value = build()
                clk.record("devcache", time.perf_counter() - t0)
                return value
            return build()
        form = str(key[0]) if key else "unknown"
        full = (self._token(snapshot), *key)
        with self._lock:
            hit = self._entries.get(full)
            if hit is not None:
                self._entries.move_to_end(full)
                self._hits += 1
        if hit is not None:
            if _telemetry_enabled():
                _metrics()["hits"].labels(form=form).inc()
            return hit
        from kubernetesclustercapacity_tpu.telemetry import phases as _phases

        clk = _phases.current()
        if clk:
            # A miss stages host padding + a host→device upload — the
            # request-visible cost the cache exists to remove.  Recorded
            # as the answering request's ``devcache`` phase (a hit
            # records nothing: that IS the cache working).
            t0 = time.perf_counter()
            value = build()
            clk.record("devcache", time.perf_counter() - t0)
        else:
            value = build()
        with self._lock:
            self._entries[full] = value
            self._entries.move_to_end(full)
            self._misses += 1
            while len(self._entries) > self._max_entries:
                self._entries.popitem(last=False)
        if _telemetry_enabled():
            _metrics()["misses"].labels(form=form).inc()
        return value

    # -- staged forms ------------------------------------------------------
    def exact_arrays(self, snapshot, *, bucket: int | None = None) -> tuple:
        """The 7 exact-kernel inputs, zero-padded to the node bucket and
        device-resident: ``(alloc_cpu, alloc_mem, alloc_pods, used_cpu,
        used_mem, pods_count, healthy)`` each ``[bucket]``.  Zero rows
        are fit-neutral in both modes; ``healthy`` pads False."""
        import jax.numpy as jnp

        n = snapshot.n_nodes
        b = node_bucket(n) if bucket is None else int(bucket)

        def build() -> tuple:
            pad = b - n
            out = []
            for a in (
                snapshot.alloc_cpu_milli,
                snapshot.alloc_mem_bytes,
                snapshot.alloc_pods,
                snapshot.used_cpu_req_milli,
                snapshot.used_mem_req_bytes,
                snapshot.pods_count,
                snapshot.healthy,
            ):
                a = np.asarray(a)
                out.append(jnp.asarray(np.pad(a, (0, pad)) if pad else a))
            return tuple(out)

        return self.get(snapshot, ("exact", b), build)

    def grouped_arrays(self, grouped, *, bucket: int | None = None) -> tuple:
        """The 8 grouped-kernel inputs (7 shape columns + counts),
        zero-padded to the GROUP bucket and device-resident — the pow2
        ladder now buckets *groups*, so a degenerate million-node fleet
        stages O(groups) device bytes, not O(nodes).  Zero-count padded
        rows contribute nothing to the weighted sum.  Keyed on the
        PARENT snapshot (the grouped form is memoized on it), under the
        ``"grouped"`` form label."""
        import jax.numpy as jnp

        snapshot = grouped.snapshot
        g = grouped.n_groups
        b = node_bucket(g) if bucket is None else int(bucket)

        def build() -> tuple:
            pad = b - g
            out = []
            for a in (
                grouped.alloc_cpu_milli,
                grouped.alloc_mem_bytes,
                grouped.alloc_pods,
                grouped.used_cpu_req_milli,
                grouped.used_mem_req_bytes,
                grouped.pods_count,
                grouped.healthy,
                grouped.count,
            ):
                a = np.asarray(a)
                out.append(jnp.asarray(np.pad(a, (0, pad)) if pad else a))
            return tuple(out)

        # The kernel consumes the first 7 positionally; the staged counts
        # ride in slot 8 for unmasked sweeps (a node_mask replaces them
        # with per-request effective counts).
        return self.get(snapshot, ("grouped", b), build)

    def grouped_pallas_arrays(self, grouped) -> tuple:
        """The 6 fused-kernel GROUP operands in kernel layout plus the
        int32 count tiles, padded to the Pallas tile grid and
        device-resident (form ``"grouped"`` with the fused tile shape in
        the key)."""
        import jax.numpy as jnp

        from kubernetesclustercapacity_tpu.ops.pallas_fit import (
            pad_node_array,
            padded_node_shape,
        )

        snapshot = grouped.snapshot
        n_pad = padded_node_shape(grouped.n_groups)

        def build() -> tuple:
            return tuple(
                jnp.asarray(pad_node_array(a, n_pad, kib=kib))
                for a, kib in (
                    (grouped.alloc_cpu_milli, False),
                    (grouped.alloc_mem_bytes, True),
                    (grouped.alloc_pods, False),
                    (grouped.used_cpu_req_milli, False),
                    (grouped.used_mem_req_bytes, True),
                    (grouped.pods_count, False),
                )
            )

        return self.get(snapshot, ("grouped", "pallas", n_pad), build)

    def pallas_arrays(self, snapshot) -> tuple:
        """The 6 fused-kernel node operands in kernel layout
        (``(n_pad/LANES, LANES)`` int32, memory KiB-rescaled), padded to
        the Pallas tile grid and device-resident."""
        import jax.numpy as jnp

        from kubernetesclustercapacity_tpu.ops.pallas_fit import (
            pad_node_array,
            padded_node_shape,
        )

        n_pad = padded_node_shape(snapshot.n_nodes)

        def build() -> tuple:
            return tuple(
                jnp.asarray(pad_node_array(a, n_pad, kib=kib))
                for a, kib in (
                    (snapshot.alloc_cpu_milli, False),
                    (snapshot.alloc_mem_bytes, True),
                    (snapshot.alloc_pods, False),
                    (snapshot.used_cpu_req_milli, False),
                    (snapshot.used_mem_req_bytes, True),
                    (snapshot.pods_count, False),
                )
            )

        return self.get(snapshot, ("pallas", n_pad), build)

    # -- lifecycle ---------------------------------------------------------
    def warm(self, snapshot, forms: tuple[str, ...] = ("exact", "pallas")) -> None:
        """Pre-stage a snapshot's arrays (the coalescer publish path runs
        this on ITS worker thread so a relist never stalls a reader).
        Strictly best-effort: warming must never fail a publish."""
        for form in forms:
            try:
                if form == "exact":
                    self.exact_arrays(snapshot)
                elif form == "pallas":
                    self.pallas_arrays(snapshot)
            except Exception:  # noqa: BLE001 - warm is an optimization
                pass

    def invalidate(self, snapshot=None) -> None:
        """Drop a snapshot's entries (or everything when ``None``) —
        called on snapshot swap so retired device buffers free promptly
        instead of waiting out the LRU."""
        with self._lock:
            if snapshot is None:
                self._entries.clear()
                return
            tok = snapshot.__dict__.get("_devcache_token")
            if tok is None:
                return  # never cached: nothing to drop
            for key in [k for k in self._entries if k[0] == tok]:
                del self._entries[key]

    def stats(self) -> dict:
        """JSON-able counters for doctor / the info op / bench.py."""
        with self._lock:
            hits, misses, entries = self._hits, self._misses, len(self._entries)
        total = hits + misses
        return {
            "enabled": enabled(),
            "entries": entries,
            "hits": hits,
            "misses": misses,
            "hit_rate": (hits / total) if total else 0.0,
        }


#: The process-wide default cache (the dispatch wrappers, the server and
#: bench all share it; invalidation is per-snapshot, so co-hosted
#: servers never interfere).
CACHE = DeviceCache()
