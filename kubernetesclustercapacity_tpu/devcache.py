"""Device-resident snapshot cache + the shape-bucket ladder (hot path).

Every ``fit``/``sweep`` request used to re-upload the snapshot's seven
node arrays host→device (``jnp.asarray`` inside the dispatch) and to
compile a fresh executable whenever the node count changed by one.  The
per-request *work* is tiny; the per-request *overhead* was the product —
the same observation the inference-serving world made about KV caches
and shape buckets.  This module is both fixes in one place:

* **Device cache** — :class:`DeviceCache` holds already-``device_put``
  node arrays keyed by ``(snapshot, form, shape-bucket)``.  Snapshots
  are immutable by contract (the packers build them once; the server
  swaps whole objects on reload/update), so identity is the cache key:
  a per-snapshot token is lazily attached and entries die with LRU
  eviction or an explicit :meth:`DeviceCache.invalidate` on snapshot
  swap.  ``KCCAP_DEVCACHE=0`` disables caching AND bucketing — the
  escape hatch restores the exact pre-cache dispatch.
* **Bucket ladder** — :func:`node_bucket` pads the node axis up a small
  geometric ladder (next power of two above a configurable floor), and
  :func:`scenario_bucket` does the same for the scenario axis.  Zero
  node rows are fit-neutral in both semantics modes (proven in
  ``parallel/sweep.py``: ``alloc <= used`` guards to 0, then the Q1 cap
  rewrites ``0 >= 0`` to ``0 - 0``), and padded scenarios are harmless
  ``(1 milli, 1 byte)`` probes whose outputs are sliced off — so a
  cluster growing 1000 → 1001 nodes reuses the 1024-bucket executable
  instead of recompiling.

Cache hit/miss counters land on the process telemetry registry
(``kccap_devcache_*``); ``doctor``, the service ``info`` op and
``bench.py`` all read :meth:`DeviceCache.stats`.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from collections import OrderedDict

import numpy as np

from kubernetesclustercapacity_tpu.telemetry import memledger as _memledger

__all__ = [
    "DeviceCache",
    "CACHE",
    "enabled",
    "donate_enabled",
    "node_bucket",
    "scenario_bucket",
    "node_bucket_floor",
    "set_node_bucket_floor",
]

#: Default floor of the node-axis bucket ladder.  Below the floor every
#: cluster shares one executable; above it buckets double, so a snapshot
#: sees at most ``log2(N/floor)`` distinct compiled shapes over its life.
DEFAULT_NODE_BUCKET_FLOOR = 256

#: Scenario-axis floor: grids are usually small and request-shaped, so a
#: low floor keeps padding waste bounded while collapsing the long tail
#: of distinct S values onto a handful of executables.
SCENARIO_BUCKET_FLOOR = 16

_floor_lock = threading.Lock()
_node_floor: int | None = None


def enabled() -> bool:
    """Process-wide hot-path switch (``KCCAP_DEVCACHE=0`` disables).

    Checked per dispatch so the escape hatch works without a restart;
    off means no caching *and* no shape bucketing — byte-for-byte the
    pre-cache dispatch behavior.
    """
    return os.environ.get("KCCAP_DEVCACHE", "1") != "0"


def donate_enabled() -> bool:
    """Donated-resident-buffer switch (``KCCAP_DONATE=0`` disables).

    Checked per publish: off restores the exact pre-donation publish
    path — invalidate the retired generation, cold-stage the new one —
    byte-for-byte (pinned by test).  On, a snapshot publish re-stages
    only CHANGED columns (:meth:`DeviceCache.stage_replace`): unchanged
    columns stay device-resident across generations, and changed ones
    re-upload through a ``donate_argnums`` jit so the retired buffer's
    device memory is reusable for the incoming column instead of
    doubling peak HBM during the swap.
    """
    return os.environ.get("KCCAP_DONATE", "1") != "0"


def node_bucket_floor() -> int:
    """The active node-bucket floor (flag/env-configurable)."""
    global _node_floor
    with _floor_lock:
        if _node_floor is None:
            try:
                env = int(os.environ.get("KCCAP_NODE_BUCKET_FLOOR", "0"))
            except ValueError:
                env = 0
            _node_floor = env if env > 0 else DEFAULT_NODE_BUCKET_FLOOR
        return _node_floor


def set_node_bucket_floor(floor: int) -> None:
    """Set the node-bucket floor (``kccap-server -node-bucket-floor``)."""
    global _node_floor
    if floor < 1:
        raise ValueError("node bucket floor must be >= 1")
    with _floor_lock:
        _node_floor = int(floor)


def _next_pow2_at_least(n: int, floor: int) -> int:
    b = max(int(floor), 1)
    n = max(int(n), 1)
    while b < n:
        b <<= 1
    return b


def node_bucket(n: int, floor: int | None = None) -> int:
    """Node axis padded size: next power of two ``>= max(n, floor)``."""
    return _next_pow2_at_least(n, node_bucket_floor() if floor is None else floor)


def scenario_bucket(s: int) -> int:
    """Scenario axis padded size (fixed low floor, then powers of two)."""
    return _next_pow2_at_least(s, SCENARIO_BUCKET_FLOOR)


# Lazily-built telemetry handles on the process registry (importing this
# module must register nothing; KCCAP_TELEMETRY=0 means zero registry
# calls on the hot path — same policy as ops/pallas_fit).
_MET: dict | None = None
_met_lock = threading.Lock()


def _metrics() -> dict:
    global _MET
    if _MET is None:
        with _met_lock:
            if _MET is None:
                from kubernetesclustercapacity_tpu.telemetry.metrics import (
                    REGISTRY,
                )

                _MET = {
                    "hits": REGISTRY.counter(
                        "kccap_devcache_hits_total",
                        "Device-cache hits, by staged form.",
                        ("form",),
                    ),
                    "misses": REGISTRY.counter(
                        "kccap_devcache_misses_total",
                        "Device-cache misses (staged fresh), by form.",
                        ("form",),
                    ),
                    "donate": REGISTRY.counter(
                        "kccap_donate_columns_total",
                        "Per-column dispositions of a donated-resident "
                        "snapshot publish (stage_replace): reused = "
                        "column unchanged, kept device-resident; "
                        "donated = re-uploaded through the "
                        "donate_argnums jit; restaged = plain cold "
                        "upload (CPU backend, bucket change, or a "
                        "concurrent in-flight holder).",
                        ("disposition",),
                    ),
                }
    return _MET


def _telemetry_enabled() -> bool:
    from kubernetesclustercapacity_tpu.telemetry.metrics import enabled as en

    return en()


_DONATE_JIT = None
_donate_lock = threading.Lock()


def _donate_jit():
    """The donated-replace program, built lazily (importing this module
    must not touch JAX).  ``donate_argnums=(0,)`` marks the retired
    generation's column as dead on entry, so XLA may alias the output —
    the incoming column's bytes — into its device buffer; the select
    reads both operands, keeping the aliasing opportunity real rather
    than letting an identity program fold away.  Bit-exact: the output
    is ``new``, element for element, on every carrier dtype."""
    global _DONATE_JIT
    with _donate_lock:
        if _DONATE_JIT is None:
            import jax
            import jax.numpy as jnp

            def _replace(old, new):
                return jnp.where(jnp.bool_(True), new, old)

            _DONATE_JIT = jax.jit(_replace, donate_argnums=(0,))
    return _DONATE_JIT


def _retire_remaining(entries: "OrderedDict[tuple, object]") -> None:
    """Finalizer body for a dying :class:`DeviceCache`: un-book whatever
    it still held so the ledger never accrues stale entries.  Swallows
    everything — it can run during interpreter shutdown."""
    try:
        values = list(entries.values())
        entries.clear()
        for v in values:
            _memledger.retire(v)
    except Exception:
        pass


class DeviceCache:
    """Thread-safe LRU of device-staged node arrays, keyed per snapshot.

    Generic storage: :meth:`get` takes any hashable ``key`` (its first
    element names the *form* for the hit/miss counters) and a zero-arg
    ``build`` callable.  The exact-kernel and fused-kernel forms have
    dedicated helpers below; the GSPMD path stages through :meth:`get`
    directly with its mesh in the key.

    Keys are scoped by a token lazily attached to the snapshot object —
    snapshots are immutable by contract, so object identity IS content
    identity.  ``max_entries`` bounds device memory: each entry is
    O(bucket) per array, and a serving process holds at most the current
    and the about-to-be-published generation.
    """

    def __init__(self, max_entries: int = 8) -> None:
        self._lock = threading.Lock()
        self._entries: OrderedDict[tuple, object] = OrderedDict()
        self._max_entries = max(1, int(max_entries))
        self._hits = 0
        self._misses = 0
        self._next_token = 0
        # The ledger books entries by identity the moment they are
        # staged; if this cache object is dropped (short-lived caches in
        # tools/tests) its buffers die with it, and without this
        # finalizer the book would keep them forever — a false
        # "sustained leak" on the next reconcile.  The callback holds
        # the entries dict, never ``self``.
        weakref.finalize(self, _retire_remaining, self._entries)

    def _token(self, snapshot) -> int:
        tok = snapshot.__dict__.get("_devcache_token")
        if tok is None:
            with self._lock:
                tok = snapshot.__dict__.get("_devcache_token")
                if tok is None:
                    tok = self._next_token
                    self._next_token += 1
                    snapshot.__dict__["_devcache_token"] = tok
        return tok

    def get(self, snapshot, key: tuple, build):
        """The cached value for ``(snapshot, key)``; built once.

        ``build`` runs OUTSIDE the lock (it does host padding + a device
        transfer); a concurrent miss may build twice — last store wins,
        both values are equal by construction.
        """
        if not enabled():
            from kubernetesclustercapacity_tpu.telemetry import (
                phases as _phases,
            )

            clk = _phases.current()
            if clk:
                # Cache disabled: every request re-stages — still the
                # devcache phase (the decomposition must show what the
                # escape hatch costs).
                t0 = time.perf_counter()
                with clk.live("devcache"):
                    value = build()
                clk.record("devcache", time.perf_counter() - t0)
                return value
            return build()
        form = str(key[0]) if key else "unknown"
        full = (self._token(snapshot), *key)
        with self._lock:
            hit = self._entries.get(full)
            if hit is not None:
                self._entries.move_to_end(full)
                self._hits += 1
        if hit is not None:
            if _telemetry_enabled():
                _metrics()["hits"].labels(form=form).inc()
            return hit
        from kubernetesclustercapacity_tpu.telemetry import phases as _phases

        clk = _phases.current()
        if clk:
            # A miss stages host padding + a host→device upload — the
            # request-visible cost the cache exists to remove.  Recorded
            # as the answering request's ``devcache`` phase (a hit
            # records nothing: that IS the cache working).
            t0 = time.perf_counter()
            with clk.live("devcache"):
                value = build()
            clk.record("devcache", time.perf_counter() - t0)
        else:
            value = build()
        # Book BEFORE the value becomes poppable: once it is in
        # ``_entries`` a concurrent eviction/invalidate may retire it,
        # and a retire that races ahead of a late register would leave
        # the book with a stale leaf forever (a false sustained leak).
        if _memledger.enabled():
            _memledger.register(value, form)
        evicted: list = []
        with self._lock:
            prev = self._entries.get(full)
            if prev is not None:
                evicted.append(prev)  # double-build race: last store wins
            self._entries[full] = value
            self._entries.move_to_end(full)
            self._misses += 1
            while len(self._entries) > self._max_entries:
                evicted.append(self._entries.popitem(last=False)[1])
        for v in evicted:
            _memledger.retire(v)
        if _telemetry_enabled():
            _metrics()["misses"].labels(form=form).inc()
        return value

    # -- staged forms ------------------------------------------------------
    def exact_arrays(self, snapshot, *, bucket: int | None = None) -> tuple:
        """The 7 exact-kernel inputs, zero-padded to the node bucket and
        device-resident: ``(alloc_cpu, alloc_mem, alloc_pods, used_cpu,
        used_mem, pods_count, healthy)`` each ``[bucket]``.  Zero rows
        are fit-neutral in both modes; ``healthy`` pads False."""
        import jax.numpy as jnp

        n = snapshot.n_nodes
        b = node_bucket(n) if bucket is None else int(bucket)

        def build() -> tuple:
            pad = b - n
            out = []
            for a in (
                snapshot.alloc_cpu_milli,
                snapshot.alloc_mem_bytes,
                snapshot.alloc_pods,
                snapshot.used_cpu_req_milli,
                snapshot.used_mem_req_bytes,
                snapshot.pods_count,
                snapshot.healthy,
            ):
                a = np.asarray(a)
                out.append(jnp.asarray(np.pad(a, (0, pad)) if pad else a))
            return tuple(out)

        return self.get(snapshot, ("exact", b), build)

    def grouped_arrays(self, grouped, *, bucket: int | None = None) -> tuple:
        """The 8 grouped-kernel inputs (7 shape columns + counts),
        zero-padded to the GROUP bucket and device-resident — the pow2
        ladder now buckets *groups*, so a degenerate million-node fleet
        stages O(groups) device bytes, not O(nodes).  Zero-count padded
        rows contribute nothing to the weighted sum.  Keyed on the
        PARENT snapshot (the grouped form is memoized on it), under the
        ``"grouped"`` form label."""
        import jax.numpy as jnp

        snapshot = grouped.snapshot
        g = grouped.n_groups
        b = node_bucket(g) if bucket is None else int(bucket)

        def build() -> tuple:
            pad = b - g
            out = []
            for a in (
                grouped.alloc_cpu_milli,
                grouped.alloc_mem_bytes,
                grouped.alloc_pods,
                grouped.used_cpu_req_milli,
                grouped.used_mem_req_bytes,
                grouped.pods_count,
                grouped.healthy,
                grouped.count,
            ):
                a = np.asarray(a)
                out.append(jnp.asarray(np.pad(a, (0, pad)) if pad else a))
            return tuple(out)

        # The kernel consumes the first 7 positionally; the staged counts
        # ride in slot 8 for unmasked sweeps (a node_mask replaces them
        # with per-request effective counts).
        return self.get(snapshot, ("grouped", b), build)

    def grouped_pallas_arrays(self, grouped) -> tuple:
        """The 6 fused-kernel GROUP operands in kernel layout plus the
        int32 count tiles, padded to the Pallas tile grid and
        device-resident (form ``"grouped"`` with the fused tile shape in
        the key)."""
        import jax.numpy as jnp

        from kubernetesclustercapacity_tpu.ops.pallas_fit import (
            pad_node_array,
            padded_node_shape,
        )

        snapshot = grouped.snapshot
        n_pad = padded_node_shape(grouped.n_groups)

        def build() -> tuple:
            return tuple(
                jnp.asarray(pad_node_array(a, n_pad, kib=kib))
                for a, kib in (
                    (grouped.alloc_cpu_milli, False),
                    (grouped.alloc_mem_bytes, True),
                    (grouped.alloc_pods, False),
                    (grouped.used_cpu_req_milli, False),
                    (grouped.used_mem_req_bytes, True),
                    (grouped.pods_count, False),
                )
            )

        return self.get(snapshot, ("grouped", "pallas", n_pad), build)

    def pallas_arrays(self, snapshot) -> tuple:
        """The 6 fused-kernel node operands in kernel layout
        (``(n_pad/LANES, LANES)`` int32, memory KiB-rescaled), padded to
        the Pallas tile grid and device-resident."""
        import jax.numpy as jnp

        from kubernetesclustercapacity_tpu.ops.pallas_fit import (
            pad_node_array,
            padded_node_shape,
        )

        n_pad = padded_node_shape(snapshot.n_nodes)

        def build() -> tuple:
            return tuple(
                jnp.asarray(pad_node_array(a, n_pad, kib=kib))
                for a, kib in (
                    (snapshot.alloc_cpu_milli, False),
                    (snapshot.alloc_mem_bytes, True),
                    (snapshot.alloc_pods, False),
                    (snapshot.used_cpu_req_milli, False),
                    (snapshot.used_mem_req_bytes, True),
                    (snapshot.pods_count, False),
                )
            )

        return self.get(snapshot, ("pallas", n_pad), build)

    # -- lifecycle ---------------------------------------------------------
    def warm(self, snapshot, forms: tuple[str, ...] = ("exact", "pallas")) -> None:
        """Pre-stage a snapshot's arrays (the coalescer publish path runs
        this on ITS worker thread so a relist never stalls a reader).
        Strictly best-effort: warming must never fail a publish."""
        for form in forms:
            try:
                if form == "exact":
                    self.exact_arrays(snapshot)
                elif form == "pallas":
                    self.pallas_arrays(snapshot)
            except Exception:  # noqa: BLE001 - warm is an optimization
                pass

    def stage_replace(self, old, new) -> dict:
        """Donated-resident publish: retire ``old``'s cache entries and
        stage ``new``'s exact-form columns, re-uploading ONLY what
        changed.

        The retired generation's staged exact tuple is popped under the
        cache lock first — no new dispatch can acquire it after this
        point — then each of ``new``'s seven bucket-padded columns is
        compared bit-for-bit against ``old``'s on the host:

        * identical → the already-resident device array is carried into
          the new generation's entry (zero transfer — the common case:
          a watch event touches a handful of nodes, not the fleet);
        * changed → re-uploaded through the ``donate_argnums=(0,)`` jit
          when safe (non-CPU backend, and no in-flight dispatch still
          holds the retired tuple — donating a buffer a running kernel
          reads would be a use-after-free), so XLA may alias the new
          column into the retired buffer's HBM;
        * otherwise (CPU backend, node-bucket change, concurrent
          holder, no prior staging) → a plain cold upload, identical to
          the pre-donation path.

        Values are bit-identical in every case — the staged tuple is
        byte-equal to what :meth:`exact_arrays` would build fresh
        (pinned by test).  Non-exact forms (pallas tiles, grouped) are
        dropped with the old generation; the caller re-warms them.
        Returns ``{"reused": int, "donated": int, "restaged": int}``
        per-column dispositions (also counted on
        ``kccap_donate_columns_total``).  Callers gate on
        :func:`donate_enabled` — this method assumes the hatch is open.
        """
        import sys

        import jax
        import jax.numpy as jnp

        counts = {"reused": 0, "donated": 0, "restaged": 0}
        old_staged: dict = {}
        if old is not None and old is not new:
            retired: list = []
            with self._lock:
                tok = old.__dict__.get("_devcache_token")
                if tok is not None:
                    for key in [k for k in self._entries if k[0] == tok]:
                        v = self._entries.pop(key)
                        retired.append(v)
                        if len(key) == 3 and key[1] == "exact":
                            old_staged[key[2]] = v
            for v in retired:
                _memledger.retire(v)
        if not enabled():
            return counts
        b = node_bucket(new.n_nodes)
        prior = old_staged.get(b)
        if prior is not None and old.n_nodes > b:
            prior = None  # custom-bucket staging: shapes won't line up
        # An in-flight dispatch that grabbed the tuple before the pop
        # still holds a reference; donating its buffers would free
        # device memory out from under a running kernel.  After the pop
        # the only expected holders are `old_staged` and `prior`
        # (+1 for getrefcount's own argument) — anything above that is
        # a concurrent reader, so fall back to plain uploads.
        may_donate = (
            prior is not None
            and jax.default_backend() != "cpu"
            and sys.getrefcount(prior) <= 3
        )

        def col7(snap):
            return (
                snap.alloc_cpu_milli, snap.alloc_mem_bytes, snap.alloc_pods,
                snap.used_cpu_req_milli, snap.used_mem_req_bytes,
                snap.pods_count, snap.healthy,
            )

        pad_new = b - new.n_nodes
        pad_old = b - old.n_nodes if prior is not None else 0
        staged = []
        for i, col in enumerate(col7(new)):
            col = np.asarray(col)
            col_p = np.pad(col, (0, pad_new)) if pad_new else col
            if prior is not None:
                old_col = np.asarray(col7(old)[i])
                old_p = (
                    np.pad(old_col, (0, pad_old)) if pad_old else old_col
                )
                if np.array_equal(col_p, old_p):
                    staged.append(prior[i])
                    counts["reused"] += 1
                    continue
                if may_donate:
                    staged.append(_donate_jit()(prior[i], col_p))
                    counts["donated"] += 1
                    continue
            staged.append(jnp.asarray(col_p))
            counts["restaged"] += 1
        staged_t = tuple(staged)
        full = (self._token(new), "exact", b)  # token before the lock
        # Book before the store — same retire-races-register hazard as
        # :meth:`get`.
        if _memledger.enabled():
            _memledger.register(staged_t, "exact")
        evicted: list = []
        with self._lock:
            prev = self._entries.get(full)
            if prev is not None:
                evicted.append(prev)
            self._entries[full] = staged_t
            self._entries.move_to_end(full)
            while len(self._entries) > self._max_entries:
                evicted.append(self._entries.popitem(last=False)[1])
        for v in evicted:
            _memledger.retire(v)
        if _telemetry_enabled():
            met = _metrics()["donate"]
            for disposition, c in counts.items():
                if c:
                    met.labels(disposition=disposition).inc(c)
        return counts

    def invalidate(self, snapshot=None) -> None:
        """Drop a snapshot's entries (or everything when ``None``) —
        called on snapshot swap so retired device buffers free promptly
        instead of waiting out the LRU."""
        dropped: list = []
        with self._lock:
            if snapshot is None:
                dropped.extend(self._entries.values())
                self._entries.clear()
            else:
                tok = snapshot.__dict__.get("_devcache_token")
                if tok is None:
                    return  # never cached: nothing to drop
                for key in [k for k in self._entries if k[0] == tok]:
                    dropped.append(self._entries.pop(key))
        for v in dropped:
            _memledger.retire(v)

    def stats(self) -> dict:
        """JSON-able counters for doctor / the info op / bench.py."""
        with self._lock:
            hits, misses, entries = self._hits, self._misses, len(self._entries)
        total = hits + misses
        return {
            "enabled": enabled(),
            "entries": entries,
            "hits": hits,
            "misses": misses,
            "hit_rate": (hits / total) if total else 0.0,
        }


#: The process-wide default cache (the dispatch wrappers, the server and
#: bench all share it; invalidation is per-snapshot, so co-hosted
#: servers never interfere).
CACHE = DeviceCache()
