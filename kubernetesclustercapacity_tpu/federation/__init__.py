"""Federated multi-cluster capacity: one query plane over a fleet.

The replicated serving plane (:mod:`..service.plane`) fans ONE leader
out to N replicas; this package inverts it — N cluster leaders publish
their digest-chained generation streams INTO one
:class:`FederationServer`, which holds a verified snapshot + generation
watermark per cluster and answers fleet-global queries (``fed_sweep`` /
``fed_rank`` / ``spillover``) as one batched kernel dispatch over the
concatenated clusters.

The robustness core is the degradation contract: every reply carries a
per-cluster ``{generation, age_s, state: fresh|stale|lost}`` vector; a
partitioned cluster keeps serving its last verified snapshot marked
``stale`` until the eviction horizon flips it to ``lost`` (excluded
from totals and NAMED in the reply) — answers degrade to explicitly
stale views, never silently wrong ones.
"""

from kubernetesclustercapacity_tpu.federation.server import (
    CLUSTER_STATES,
    ClusterFeed,
    FederationError,
    FederationServer,
)

__all__ = [
    "CLUSTER_STATES",
    "ClusterFeed",
    "FederationError",
    "FederationServer",
]
