"""The federation tier: partition-tolerant fleet queries over N clusters.

Topology: each member cluster runs its own PR-10 leader (a
``kccap-server -plane-port`` fed by its own follower); the
:class:`FederationServer` subscribes to every leader's plane stream
through the SAME :class:`~..service.plane.PlaneSubscriber` machinery a
replica uses — every staged generation is digest-verified against its
frame, diffs must chain from the held digest, and a garbled, gapped, or
regressing stream is refused and resynced through a fresh checkpoint,
never mis-applied.  Each cluster's verified snapshot lands in a
:class:`ClusterFeed` (the subscriber's staging target) with a
per-cluster generation watermark that is monotone by construction.

Queries (``fed_sweep`` / ``fed_rank`` / ``spillover``) evaluate as ONE
batched kernel dispatch per semantics group: the non-lost clusters'
node arrays concatenate into a single :class:`~..snapshot
.ClusterSnapshot` (memoized per member-generation vector, so repeated
queries reuse the device-resident staging), ride the existing
devcache/bucketing/grouped stack unchanged — (shape, count) grouping
dedups shapes ACROSS clusters for free — and per-cluster totals fall
out of the per-node fit matrix by segment sums at the cluster
boundaries, bit-exact per cluster against ``fit_arrays_python`` at each
cluster's stamped generation (fit is per-node independent, so the
concatenated dispatch IS the per-cluster dispatch).

The degradation contract (the point of the module): every reply carries
a per-cluster ``{generation, age_s, state}`` vector driven by the
subscriber's :meth:`~..service.plane.PlaneSubscriber
.last_verified_age_s` clock —

* ``fresh``  — verified within ``stale_after_s``;
* ``stale``  — silent past ``stale_after_s``: the last VERIFIED
  snapshot keeps serving, explicitly annotated with its bounded age;
* ``lost``   — silent past ``evict_after_s`` (or never synced): the
  cluster is EXCLUDED from totals and NAMED in the reply's
  ``excluded`` list; cluster-scoped queries against it refuse with the
  typed ``cluster_lost`` wire code
  (:class:`~..resilience.ClusterLostError`).

``/healthz`` (the ``fed:`` watch in ``main``) goes 503 while any
cluster is lost, and heal is automatic: the subscriber resumes through
digest-match or a fresh checkpoint exactly like a plane replica, and
the next verified frame flips the cluster back to ``fresh``.
"""

from __future__ import annotations

import os
import socketserver
import threading
import time

import numpy as np

from kubernetesclustercapacity_tpu.masks import implicit_taint_mask
from kubernetesclustercapacity_tpu.ops.fit import sweep_snapshot
from kubernetesclustercapacity_tpu.resilience import ClusterLostError
from kubernetesclustercapacity_tpu.scenario import (
    ScenarioError,
    ScenarioGrid,
    scenario_from_flags,
)
from kubernetesclustercapacity_tpu.service import protocol
from kubernetesclustercapacity_tpu.snapshot import ClusterSnapshot

__all__ = [
    "CLUSTER_STATES",
    "ClusterFeed",
    "FederationError",
    "FederationServer",
    "concat_snapshots",
]

#: The degradation-contract vocabulary, in health order.
CLUSTER_STATES = ("fresh", "stale", "lost")

#: Env defaults for the staleness/eviction horizons (the ``kccap-fed``
#: flags override; both in seconds on the injectable monotonic clock).
_STALE_ENV = "KCCAP_FED_STALE_AFTER_S"
_EVICT_ENV = "KCCAP_FED_EVICT_AFTER_S"


class FederationError(RuntimeError):
    """Federation-tier configuration/query violation (bad cluster name,
    regressing generation injection, malformed query)."""


class ClusterFeed:
    """A :class:`~..service.plane.PlaneSubscriber` staging target that
    is NOT a server: it holds one cluster's last verified snapshot and
    generation watermark under a lock.

    Quacks exactly enough like a :class:`~..service.server
    .CapacityServer` for the subscriber to stage into it
    (``replace_snapshot(snapshot, generation=...)`` /
    ``set_plane_role`` / ``add_drain_hook``), so the federation tier
    inherits the replica's entire verification story — digest chains,
    checkpoint resync, regression refusal — without duplicating a line
    of it.  The generation watermark is monotone by construction: a
    regressing stage raises (the subscriber already refuses to send
    one; this guard keeps direct injectors honest too).
    """

    def __init__(self, name: str, *, clock=time.monotonic) -> None:
        self.name = name
        self._lock = threading.Lock()
        self._clock = clock
        self._snapshot: ClusterSnapshot | None = None
        self._generation = 0
        self._verified_at: float | None = None
        self._applied = 0
        self._plane_stats_source = None

    # -- the stage funnel (PlaneSubscriber's server surface) ---------------
    def replace_snapshot(
        self,
        snapshot: ClusterSnapshot,
        fixture=None,
        *,
        fixture_source=None,
        warm: bool = False,
        generation: int | None = None,
    ) -> None:
        with self._lock:
            gen = (
                self._generation + 1 if generation is None else int(generation)
            )
            if gen < self._generation:
                raise ValueError(
                    f"cluster {self.name!r}: generation must not regress: "
                    f"{gen} < held {self._generation}"
                )
            self._snapshot = snapshot
            self._generation = gen
            self._verified_at = self._clock()
            self._applied += 1

    def set_plane_role(self, role: str, stats_source=None) -> None:
        """The subscriber declares this feed a replica-side stage; keep
        its stats source so fed status can surface stream health."""
        with self._lock:
            if stats_source is not None:
                self._plane_stats_source = stats_source

    def add_drain_hook(self, hook) -> None:
        """Feeds have no drain lifecycle of their own (the federation
        server stops its subscribers directly)."""

    # -- read side ---------------------------------------------------------
    def view(self) -> tuple[ClusterSnapshot | None, int]:
        """The held (snapshot, generation) pair, atomically."""
        with self._lock:
            return self._snapshot, self._generation

    def last_verified_age_s(self) -> float | None:
        """Seconds since the feed last staged a verified generation
        (``None`` before the first) — the OFFLINE-injection freshness
        clock; wire-fed clusters read the subscriber's
        ``last_verified_age_s`` instead, which heartbeats also advance."""
        with self._lock:
            if self._verified_at is None:
                return None
            return self._clock() - self._verified_at

    def stream_stats(self) -> dict | None:
        """The subscriber's stats dict (via the stats source it handed
        ``set_plane_role``), or ``None`` for offline-injected feeds."""
        with self._lock:
            source = self._plane_stats_source
        if source is None:
            return None
        try:
            return source()
        except Exception as e:  # noqa: BLE001 - status must not fail reads
            return {"error": f"{type(e).__name__}: {e}"}


class _Cluster:
    """One federation member: its feed and (for wire-fed members) the
    plane subscriber following its leader."""

    def __init__(self, name: str, feed: ClusterFeed, subscriber=None) -> None:
        self.name = name
        self.feed = feed
        self.subscriber = subscriber

    def age_s(self) -> float | None:
        """The ONE staleness clock: the subscriber's verified age for
        wire-fed clusters (heartbeats keep a quiet-but-live leader
        fresh), the feed's stage age for offline-injected ones."""
        if self.subscriber is not None:
            return self.subscriber.last_verified_age_s()
        return self.feed.last_verified_age_s()


# ---------------------------------------------------------------------------
# Snapshot concatenation (the one-dispatch trick)
# ---------------------------------------------------------------------------
def concat_snapshots(snaps: list[ClusterSnapshot]) -> ClusterSnapshot:
    """Concatenate same-semantics cluster snapshots along the node axis.

    The combined snapshot is a first-class :class:`ClusterSnapshot`, so
    the whole dispatch stack — device cache, shape buckets, (shape,
    count) grouping (which now dedups shapes ACROSS clusters) — applies
    unchanged.  Row order is the member order, so per-cluster results
    are contiguous slices of any per-node output.  Extended columns are
    dropped: the plane's wire vocabulary never carries them, and the
    federation surface is the 2-resource fit (documented in the README).
    """
    if len(snaps) == 1:
        return snaps[0]
    any_taints = any(any(s.taints or []) for s in snaps)
    taints: list[list] = []
    if any_taints:
        for s in snaps:
            t = list(s.taints or [])
            if len(t) != s.n_nodes:
                t = [[] for _ in range(s.n_nodes)]
            taints.extend(t)
    return ClusterSnapshot(
        names=[n for s in snaps for n in s.names],
        alloc_cpu_milli=np.concatenate([s.alloc_cpu_milli for s in snaps]),
        alloc_mem_bytes=np.concatenate([s.alloc_mem_bytes for s in snaps]),
        alloc_pods=np.concatenate([s.alloc_pods for s in snaps]),
        used_cpu_req_milli=np.concatenate(
            [s.used_cpu_req_milli for s in snaps]
        ),
        used_cpu_lim_milli=np.concatenate(
            [s.used_cpu_lim_milli for s in snaps]
        ),
        used_mem_req_bytes=np.concatenate(
            [s.used_mem_req_bytes for s in snaps]
        ),
        used_mem_lim_bytes=np.concatenate(
            [s.used_mem_lim_bytes for s in snaps]
        ),
        pods_count=np.concatenate([s.pods_count for s in snaps]),
        healthy=np.concatenate([s.healthy for s in snaps]),
        semantics=snaps[0].semantics,
        taints=taints,
    )


# ---------------------------------------------------------------------------
# Wire plumbing (same framed-JSON protocol as the capacity service)
# ---------------------------------------------------------------------------
class _FedHandler(socketserver.BaseRequestHandler):
    def handle(self) -> None:  # one connection, many frames
        fed: "FederationServer" = self.server.federation_server  # type: ignore[attr-defined]
        while True:
            try:
                msg = protocol.recv_msg(self.request)
            except (protocol.ProtocolError, OSError):
                return
            if msg is None:
                return
            try:
                reply = {"ok": True, "result": fed.dispatch(msg)}
            except Exception as e:  # noqa: BLE001 - service boundary
                reply = {"ok": False, "error": f"{type(e).__name__}: {e}"}
                code = getattr(e, "wire_code", None)
                if isinstance(code, str):
                    reply["code"] = code
            try:
                protocol.send_msg(self.request, reply)
            except OSError:
                return


class _FedTCPServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


class FederationServer:
    """Hold N clusters' verified snapshots; answer fleet-global queries.

    ``clusters`` maps cluster name → plane ``(host, port)`` (each a
    PR-10 leader's ``-plane-port``); a :class:`~..service.plane
    .PlaneSubscriber` follows each stream into that cluster's
    :class:`ClusterFeed`.  :meth:`inject` feeds a cluster WITHOUT a
    wire (offline what-ifs, the bench's simulated fleet, tests).

    ``stale_after_s`` / ``evict_after_s`` are the degradation horizons
    (defaults: ``KCCAP_FED_STALE_AFTER_S`` / ``KCCAP_FED_EVICT_AFTER_S``
    env, then 10 s / 60 s); ``clock`` injects the monotonic clock those
    horizons are measured on, so chaos tests pin exact transitions.
    """

    _KNOWN_OPS = frozenset(
        {"ping", "info", "fed_status", "fed_sweep", "fed_rank", "spillover"}
    )

    def __init__(
        self,
        clusters: dict[str, tuple[str, int]] | None = None,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        stale_after_s: float | None = None,
        evict_after_s: float | None = None,
        auth_token: str | None = None,
        plane_token: str | None = None,
        registry=None,
        clock=time.monotonic,
        seed: int | None = None,
        trace_log=None,
        trace_sample: str = "always",
    ) -> None:
        """``trace_log`` (a path or :class:`~..telemetry.TraceLog`)
        records one span tree per federation query: a ``fed:{op}``
        request span plus one ``fed:member`` child per cluster in the
        fleet — lost clusters included, marked ``state: "lost"``, so a
        trace of a degraded query SHOWS the hole instead of silently
        omitting it.  ``trace_sample`` follows the ``-trace-sample``
        grammar (see :func:`~..telemetry.tracectx.parse_sample_spec`)."""
        if stale_after_s is None:
            stale_after_s = float(os.environ.get(_STALE_ENV, 10.0))
        if evict_after_s is None:
            evict_after_s = float(os.environ.get(_EVICT_ENV, 60.0))
        if not stale_after_s > 0:
            raise ValueError(
                f"stale_after_s must be > 0, got {stale_after_s}"
            )
        if not evict_after_s > stale_after_s:
            raise ValueError(
                f"evict_after_s ({evict_after_s}) must exceed "
                f"stale_after_s ({stale_after_s}): a cluster must pass "
                "through explicit staleness before it can be lost"
            )
        self.stale_after_s = float(stale_after_s)
        self.evict_after_s = float(evict_after_s)
        self._clock = clock
        self._auth_token = auth_token
        self._plane_token = plane_token
        self._seed = seed
        self._lock = threading.Lock()
        self._clusters: dict[str, _Cluster] = {}
        # Per-semantics memo of the last concatenated snapshot, keyed by
        # the member (name, generation) vector — repeated queries of an
        # unchanged fleet reuse one device-resident staging.
        self._combined_cache: dict[str, tuple[tuple, ClusterSnapshot]] = {}
        self._m_up = None
        self._m_stale = None
        self._m_gen = None
        self._m_sweeps = None
        if isinstance(trace_log, str):
            from kubernetesclustercapacity_tpu.telemetry.tracing import (
                TraceLog,
            )

            trace_log = TraceLog(trace_log)
        self._trace_sink = None
        if trace_log is not None:
            from kubernetesclustercapacity_tpu.telemetry.tracectx import (
                TailSampler,
            )

            self._trace_sink = TailSampler(
                trace_log, trace_sample, registry=registry
            )
        # Per-dispatch-thread scratch: the survey vector the handler
        # saw (and how long evaluation took), read back by dispatch()
        # to emit the fed:member child spans.
        self._dispatch_tls = threading.local()
        self.registry = registry
        if registry is not None:
            from kubernetesclustercapacity_tpu.telemetry.metrics import (
                enabled as _telemetry_enabled,
            )

            if _telemetry_enabled():
                self._m_up = registry.gauge(
                    "kccap_fed_cluster_up",
                    "1 while the cluster's view is fresh, else 0.",
                    ("cluster",),
                )
                self._m_stale = registry.gauge(
                    "kccap_fed_staleness_seconds",
                    "Seconds since the cluster's view was last verified "
                    "(-1 before the first verification).",
                    ("cluster",),
                )
                self._m_gen = registry.gauge(
                    "kccap_fed_generation",
                    "The cluster's verified generation watermark.",
                    ("cluster",),
                )
                self._m_sweeps = registry.counter(
                    "kccap_fed_sweep_total",
                    "Batched federation kernel dispatches "
                    "(fed_sweep/fed_rank/spillover evaluations).",
                )
        for name, addr in (clusters or {}).items():
            self.attach(name, addr)
        self._tcp = _FedTCPServer((host, port), _FedHandler)
        self._tcp.federation_server = self  # type: ignore[attr-defined]
        self._thread: threading.Thread | None = None

    # -- membership --------------------------------------------------------
    def attach(self, name: str, plane_addr: tuple[str, int]) -> None:
        """Subscribe to one cluster leader's plane stream.  The
        subscriber resyncs through digest-match or a fresh checkpoint on
        every reconnect — exactly the replica contract."""
        from kubernetesclustercapacity_tpu.service.plane import (
            PlaneSubscriber,
        )

        feed = ClusterFeed(name, clock=self._clock)
        # Register BEFORE the subscriber starts staging, so no generation
        # can ever land in a feed with no cluster to answer for it.
        order = self._register(name, feed, None)
        sub = PlaneSubscriber(
            tuple(plane_addr),
            feed,
            token=self._plane_token,
            stale_after_s=self.stale_after_s,
            clock=self._clock,
            seed=None if self._seed is None else self._seed + len(order),
        )
        with self._lock:
            self._clusters[name].subscriber = sub

    def _register(self, name: str, feed: ClusterFeed, subscriber):
        """Insert one cluster record (refusing duplicates) and bind its
        callback gauges; returns the post-insert cluster list (the
        deterministic per-cluster seed derives from its length)."""
        cluster = _Cluster(name, feed, subscriber)
        with self._lock:
            if name in self._clusters:
                raise FederationError(f"duplicate cluster name {name!r}")
            self._clusters[name] = cluster
            out = list(self._clusters)
        if self._m_up is not None:
            # Callback gauges: the scrape reads the CURRENT state, so a
            # cluster going stale between queries is visible without a
            # background ticker.
            self._m_up.labels(cluster=name).set_function(
                lambda c=cluster: (
                    1.0 if self._cluster_state(c)[0] == "fresh" else 0.0
                )
            )
            self._m_stale.labels(cluster=name).set_function(
                lambda c=cluster: (
                    -1.0 if c.age_s() is None else round(c.age_s(), 3)
                )
            )
            self._m_gen.labels(cluster=name).set_function(
                lambda c=cluster: float(c.feed.view()[1])
            )
        return out

    def inject(
        self,
        name: str,
        snapshot: ClusterSnapshot,
        *,
        generation: int | None = None,
    ) -> None:
        """Feed one cluster's verified snapshot WITHOUT a wire (offline
        what-ifs, the bench's simulated fleet).  Creates the cluster on
        first use; the feed's monotone-generation guard still applies."""
        with self._lock:
            cluster = self._clusters.get(name)
        if cluster is None:
            feed = ClusterFeed(name, clock=self._clock)
            try:
                self._register(name, feed, None)
            except FederationError:
                pass  # a concurrent injector created it first
            with self._lock:
                cluster = self._clusters[name]
        cluster.feed.replace_snapshot(snapshot, generation=generation)

    def _clusters_snapshot(self) -> list[_Cluster]:
        with self._lock:
            return list(self._clusters.values())

    # -- the degradation state machine -------------------------------------
    def _cluster_state(self, cluster: _Cluster) -> tuple[str, float | None]:
        """(state, age_s) for one cluster, from the ONE verified-age
        clock.  Never-synced clusters are ``lost`` (there is no view to
        serve, stale or otherwise)."""
        snap, _gen = cluster.feed.view()
        age = cluster.age_s()
        if snap is None or age is None:
            return "lost", age
        if age <= self.stale_after_s:
            return "fresh", age
        if age <= self.evict_after_s:
            return "stale", age
        return "lost", age

    def _survey(self):
        """One consistent pass over the fleet: the per-cluster
        degradation vector, the non-lost members (with their snapshots
        at their stamped generations), and the named exclusions."""
        vector: dict[str, dict] = {}
        included: list[tuple[str, ClusterSnapshot, int]] = []
        excluded: list[str] = []
        for cluster in self._clusters_snapshot():
            snap, gen = cluster.feed.view()
            state, age = self._cluster_state(cluster)
            vector[cluster.name] = {
                "generation": gen,
                "age_s": None if age is None else round(age, 3),
                "state": state,
            }
            if state == "lost":
                excluded.append(cluster.name)
            else:
                included.append((cluster.name, snap, gen))
        return vector, included, excluded

    # -- the batched evaluation core ---------------------------------------
    def _combined_for(self, semantics: str, members) -> ClusterSnapshot:
        key = tuple((name, gen) for name, _snap, gen in members)
        with self._lock:
            cached = self._combined_cache.get(semantics)
        if cached is not None and cached[0] == key:
            return cached[1]
        combined = concat_snapshots([snap for _name, snap, _gen in members])
        with self._lock:
            self._combined_cache[semantics] = (key, combined)
        return combined

    def _per_cluster_totals(self, included, grid: ScenarioGrid) -> dict:
        """``{cluster: totals[S]}`` over the non-lost members — one
        batched dispatch per semantics group (normally one), per-cluster
        totals recovered as segment sums of the per-node fit matrix at
        the cluster boundaries."""
        groups: dict[str, list] = {}
        for member in included:
            groups.setdefault(member[1].semantics, []).append(member)
        per_cluster: dict[str, np.ndarray] = {}
        for semantics, members in groups.items():
            combined = self._combined_for(semantics, members)
            _totals, _sched, fits = sweep_snapshot(
                combined,
                grid,
                mode=semantics,
                return_per_node=True,
                node_mask=implicit_taint_mask(combined),
            )
            if self._m_sweeps is not None:
                self._m_sweeps.inc()
            fits = np.asarray(fits)
            offset = 0
            for name, snap, _gen in members:
                n = snap.n_nodes
                per_cluster[name] = np.asarray(
                    fits[:, offset : offset + n].sum(axis=1), dtype=np.int64
                )
                offset += n
        return per_cluster

    # -- ops ----------------------------------------------------------------
    def dispatch(self, msg: dict) -> dict | str:
        op = msg.get("op")
        if op == "ping":
            return "pong"
        if self._auth_token is not None:
            import hmac

            token = msg.get("token")
            if not isinstance(token, str) or not hmac.compare_digest(
                token.encode(), self._auth_token.encode()
            ):
                raise PermissionError("missing or invalid auth token")
        if self._trace_sink is None:
            return self._route(op, msg)
        # Traced dispatch: the fed:{op} request span plus one
        # fed:member child per cluster (from the survey vector the
        # handler stashed) — emitted at request END so the whole tree
        # rides one tail-sampling verdict.
        from kubernetesclustercapacity_tpu.telemetry import (
            tracectx as _tracectx,
        )

        ctx = _tracectx.from_wire(msg)
        parent = msg.get("parent_span_id")
        if not isinstance(parent, str) or not parent:
            parent = None
        self._dispatch_tls.survey = None
        wall0 = time.time()
        t0 = time.perf_counter()
        error: str | None = None
        try:
            return self._route(op, msg)
        except Exception as e:
            error = f"{type(e).__name__}: {e}"
            raise
        finally:
            survey = getattr(self._dispatch_tls, "survey", None)
            self._dispatch_tls.survey = None
            if ctx is not None:
                dur = time.perf_counter() - t0
                op_label = op if op in self._KNOWN_OPS else "unknown"
                if survey is not None:
                    vector, eval_s = survey
                    for name, entry in sorted(vector.items()):
                        lost = entry.get("state") == "lost"
                        _tracectx.span(
                            self._trace_sink,
                            ts=time.time(),
                            trace_id=ctx.trace_id,
                            span_id=_tracectx.new_span_id(),
                            parent_span_id=ctx.span_id,
                            op="fed:member",
                            service="fed",
                            cluster=name,
                            state=entry.get("state"),
                            generation=entry.get("generation"),
                            # Included members shared ONE batched
                            # evaluation; a lost member costs nothing
                            # (and contributes nothing).
                            duration_ms=(
                                0.0 if lost else round(eval_s * 1e3, 3)
                            ),
                            status="error" if lost else "ok",
                            **({"error": "cluster lost"} if lost else {}),
                        )
                _tracectx.span(
                    self._trace_sink,
                    ts=time.time(),
                    start_ts=wall0,
                    trace_id=ctx.trace_id,
                    span_id=ctx.span_id,
                    **({"parent_span_id": parent} if parent else {}),
                    op=f"fed:{op_label}",
                    service="fed",
                    hops=ctx.hops,
                    duration_ms=round(dur * 1e3, 3),
                    status="error" if error else "ok",
                    **({"error": error} if error else {}),
                )
                keep = self._trace_sink.decide(
                    op_label, dur, error, forced=ctx.sampled
                )
                self._trace_sink.finish(ctx.trace_id, keep=keep)

    def _route(self, op, msg: dict) -> dict | str:
        if op == "info":
            return self._op_info()
        if op == "fed_status":
            return self.status()
        if op == "fed_sweep":
            return self._op_fed_sweep(msg)
        if op == "fed_rank":
            return self._op_fed_rank(msg)
        if op == "spillover":
            return self._op_spillover(msg)
        raise ValueError(f"unknown op {op!r}")

    def tracing_stats(self) -> dict:
        """Tracing posture for doctor: is the fed endpoint emitting
        spans, and what is the tail sampler holding/dropping."""
        out: dict = {"armed": self._trace_sink is not None}
        if self._trace_sink is not None:
            out.update(self._trace_sink.stats())
        return out

    def _op_info(self) -> dict:
        status = self.status()
        return {
            "clusters": status["counts"]["total"],
            "federation": status,
            "tracing": self.tracing_stats(),
            # The handshake vocabulary multi-endpoint clients gate on:
            # this endpoint speaks federation ops, not the single-server
            # compute surface.
            "capabilities": {"protocol": 2, "federation": True},
            "draining": False,
        }

    def status(self) -> dict:
        """The ``fed_status`` answer: the degradation vector, state
        counts, the horizons, and per-cluster stream health."""
        vector, _included, excluded = self._survey()
        counts = {s: 0 for s in CLUSTER_STATES}
        for entry in vector.values():
            counts[entry["state"]] += 1
        counts["total"] = len(vector)
        streams = {}
        for cluster in self._clusters_snapshot():
            stats = cluster.feed.stream_stats()
            if stats is not None:
                streams[cluster.name] = stats
        return {
            "enabled": bool(vector),
            "clusters": vector,
            "counts": counts,
            "excluded": excluded,
            "stale_after_s": self.stale_after_s,
            "evict_after_s": self.evict_after_s,
            "healthy": counts["lost"] == 0,
            **({"streams": streams} if streams else {}),
        }

    def healthy(self) -> bool:
        """The ``fed:`` health verdict: False while ANY cluster is lost
        (``main`` wires it to ``/healthz`` 503)."""
        _vector, _included, excluded = self._survey()
        return not excluded

    @staticmethod
    def _grid_from_msg(msg: dict) -> ScenarioGrid:
        """Array form (the sweep op's grammar) or the six reference
        flags as a single-scenario grid — one query vocabulary for the
        CLI and programmatic callers."""
        if "cpu_request_milli" in msg:
            try:
                grid = ScenarioGrid(
                    cpu_request_milli=np.asarray(msg["cpu_request_milli"]),
                    mem_request_bytes=np.asarray(msg["mem_request_bytes"]),
                    replicas=np.asarray(msg.get("replicas", [1])),
                )
                grid.validate()
            except (ScenarioError, KeyError, TypeError, ValueError) as e:
                raise ValueError(f"bad federation grid: {e}") from e
            return grid
        try:
            scenario = scenario_from_flags(
                cpuRequests=msg.get("cpuRequests", "100m"),
                cpuLimits=msg.get("cpuLimits", "200m"),
                memRequests=msg.get("memRequests", "100mb"),
                memLimits=msg.get("memLimits", "200mb"),
                replicas=msg.get("replicas", "1"),
            )
            scenario.validate()
        except ScenarioError as e:
            raise ValueError(str(e)) from e
        return ScenarioGrid.from_scenarios([scenario])

    def _op_fed_sweep(self, msg: dict) -> dict:
        """"Across all clusters, how many replicas fit, and where?" —
        grand totals over the non-lost clusters plus the per-cluster
        split, every row annotated by the degradation vector."""
        grid = self._grid_from_msg(msg)
        vector, included, excluded = self._survey()
        self._dispatch_tls.survey = (vector, 0.0)
        t_eval0 = time.perf_counter()
        per_cluster = self._per_cluster_totals(included, grid)
        self._dispatch_tls.survey = (
            vector,
            time.perf_counter() - t_eval0,
        )
        s = grid.size
        totals = np.zeros(s, dtype=np.int64)
        for t in per_cluster.values():
            totals = totals + t
        replicas = np.asarray(grid.replicas, dtype=np.int64)
        return {
            "totals": totals.tolist(),
            "schedulable": (totals >= replicas).tolist(),
            "scenarios": s,
            "per_cluster": {
                name: t.tolist() for name, t in per_cluster.items()
            },
            "clusters": vector,
            "excluded": excluded,
            "degraded": any(
                entry["state"] != "fresh" for entry in vector.values()
            ),
        }

    def _op_fed_rank(self, msg: dict) -> dict:
        """Placement ranking per cluster for ONE scenario: fitting
        clusters first — cheapest first when a ``costs`` map rides the
        request, most-headroom otherwise — then the rest by headroom.
        Lost clusters never rank (they are named in ``excluded``)."""
        grid = self._grid_from_msg(msg)
        if grid.size != 1:
            raise ValueError(
                f"fed_rank ranks one scenario, got {grid.size}"
            )
        costs = msg.get("costs") or {}
        if not isinstance(costs, dict):
            raise ValueError(f"costs must be an object, got {costs!r}")
        vector, included, excluded = self._survey()
        self._dispatch_tls.survey = (vector, 0.0)
        t_eval0 = time.perf_counter()
        per_cluster = self._per_cluster_totals(included, grid)
        self._dispatch_tls.survey = (
            vector,
            time.perf_counter() - t_eval0,
        )
        replicas = int(np.asarray(grid.replicas)[0])
        rows = []
        for name, _snap, gen in included:
            total = int(per_cluster[name][0])
            rows.append(
                {
                    "cluster": name,
                    "total": total,
                    "schedulable": total >= replicas,
                    "cost": costs.get(name),
                    "generation": gen,
                    "state": vector[name]["state"],
                    "age_s": vector[name]["age_s"],
                }
            )
        rows.sort(
            key=lambda r: (
                not r["schedulable"],  # fitting clusters first
                r["cost"] is None,  # known cost beats unknown cost
                r["cost"] if r["cost"] is not None else 0.0,
                -r["total"],
                r["cluster"],
            )
        )
        for i, row in enumerate(rows):
            row["rank"] = i + 1
        return {
            "ranking": rows,
            "replicas": replicas,
            "clusters": vector,
            "excluded": excluded,
        }

    def _op_spillover(self, msg: dict) -> dict:
        """"Drain cluster X — where does its load land?"  Demand
        defaults to X's current pod count (its load, modeled as
        scenario-shaped replicas; override with ``demand``); the rest of
        the fleet absorbs it greedily, most headroom first.  A LOST X
        refuses with the typed ``cluster_lost`` code — there is no view
        of its load to drain, not even a stale one."""
        target = msg.get("cluster")
        if not isinstance(target, str) or not target:
            raise ValueError("spillover wants a non-empty cluster name")
        grid = self._grid_from_msg(msg)
        if grid.size != 1:
            raise ValueError(
                f"spillover evaluates one scenario, got {grid.size}"
            )
        vector, included, excluded = self._survey()
        self._dispatch_tls.survey = (vector, 0.0)
        if target not in vector:
            raise FederationError(f"unknown cluster {target!r}")
        if vector[target]["state"] == "lost":
            raise ClusterLostError(
                f"cluster {target!r} is lost (generation "
                f"{vector[target]['generation']}, age "
                f"{vector[target]['age_s']}s past the "
                f"{self.evict_after_s:g}s eviction horizon); its load is "
                "unknowable — resync it or query another federation "
                "endpoint"
            )
        t_eval0 = time.perf_counter()
        per_cluster = self._per_cluster_totals(included, grid)
        self._dispatch_tls.survey = (
            vector,
            time.perf_counter() - t_eval0,
        )
        target_snap = next(s for n, s, _g in included if n == target)
        demand = msg.get("demand")
        if demand is None:
            demand = int(np.asarray(target_snap.pods_count).sum())
        elif isinstance(demand, bool) or not isinstance(demand, int):
            raise ValueError(f"demand must be an integer, got {demand!r}")
        elif demand < 0:
            raise ValueError(f"demand must be >= 0, got {demand}")
        candidates = sorted(
            (
                (int(per_cluster[name][0]), name)
                for name, _snap, _gen in included
                if name != target
            ),
            key=lambda t: (-t[0], t[1]),
        )
        remaining = int(demand)
        placements = []
        for headroom, name in candidates:
            take = min(remaining, max(headroom, 0))
            placements.append(
                {"cluster": name, "replicas": take, "headroom": headroom,
                 "state": vector[name]["state"]}
            )
            remaining -= take
        return {
            "cluster": target,
            "demand": int(demand),
            "placements": placements,
            "unplaced": remaining,
            "absorbed": remaining == 0,
            "clusters": vector,
            "excluded": excluded,
        }

    # -- lifecycle ---------------------------------------------------------
    @property
    def address(self) -> tuple[str, int]:
        return self._tcp.server_address  # type: ignore[return-value]

    def start(self) -> "FederationServer":
        self._serving = True
        self._thread = threading.Thread(
            target=self._tcp.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self._serving = True
        self._tcp.serve_forever()

    def close(self) -> None:
        """Stop every cluster subscriber, then the query listener."""
        for cluster in self._clusters_snapshot():
            if cluster.subscriber is not None:
                cluster.subscriber.stop()
        if getattr(self, "_serving", False):
            self._tcp.shutdown()
        self._tcp.server_close()

    def __enter__(self) -> "FederationServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def main(argv=None) -> int:
    """``kccap-fed -cluster east=h1:7100 -cluster west=h2:7100 -port 7177``"""
    import argparse
    import sys

    p = argparse.ArgumentParser(prog="kccap-fed")
    p.add_argument("-cluster", action="append", default=[], metavar="NAME=HOST:PORT",
                   help="subscribe to one cluster leader's plane stream "
                        "(its kccap-server -plane-port); repeatable, at "
                        "least one required")
    p.add_argument("-port", type=int, default=7177,
                   help="serve federation queries (fed_sweep/fed_rank/"
                        "spillover/fed_status) on this port")
    p.add_argument("-host", default="127.0.0.1")
    p.add_argument("-fed-stale-after-s", type=float, default=None,
                   dest="fed_stale_after_s", metavar="SECONDS",
                   help="staleness bound: a cluster silent past this "
                        "serves its last verified snapshot explicitly "
                        "marked stale (default: $KCCAP_FED_STALE_AFTER_S "
                        "or 10)")
    p.add_argument("-fed-evict-after-s", type=float, default=None,
                   dest="fed_evict_after_s", metavar="SECONDS",
                   help="eviction horizon: a cluster silent past this "
                        "flips to lost — excluded from totals, named in "
                        "every reply, /healthz 503 (default: "
                        "$KCCAP_FED_EVICT_AFTER_S or 60)")
    p.add_argument("-metrics-port", type=int, default=0, dest="metrics_port",
                   metavar="PORT",
                   help="serve Prometheus /metrics and /healthz (the "
                        "fed: watch — 503 while any cluster is lost) on "
                        "this port (0 = disabled)")
    p.add_argument("-auth-token-file", default=None, dest="auth_token_file",
                   help="file holding the shared bearer token; when set "
                        "(or $KCCAP_AUTH_TOKEN is), every op except ping "
                        "must carry it, and plane subscriptions present "
                        "it to the cluster leaders")
    p.add_argument("-trace-log", default=None, dest="trace_log",
                   metavar="PATH",
                   help="append fed:{op} request spans and fed:member "
                        "per-cluster child spans as JSONL here (feeds "
                        "kccap -trace-tree)")
    p.add_argument("-trace-log-max-bytes", type=int, default=16 * 2**20,
                   dest="trace_log_max_bytes", metavar="BYTES",
                   help="rotate the trace log at this size (one .1 "
                        "rollover, default 16MiB)")
    p.add_argument("-trace-sample", default="always", dest="trace_sample",
                   metavar="SPEC",
                   help="tail-sampling policy for span bodies: always | "
                        "p99-breach | errors | rate:N (span IDs still "
                        "propagate when bodies are dropped)")
    args = p.parse_args(argv)

    auth_token = os.environ.get("KCCAP_AUTH_TOKEN") or None
    if args.auth_token_file:
        try:
            with open(args.auth_token_file, encoding="utf-8") as fh:
                auth_token = fh.read().strip()
        except OSError as e:
            print(f"ERROR : cannot read auth token file: {e}",
                  file=sys.stderr)
            return 1
        if not auth_token:
            print("ERROR : auth token file is empty", file=sys.stderr)
            return 1
    clusters: dict[str, tuple[str, int]] = {}
    for spec in args.cluster:
        name, eq, addr = spec.partition("=")
        host_s, _, port_s = addr.rpartition(":")
        if not name or not eq or not host_s or not port_s.isdigit():
            print(
                f"ERROR : bad -cluster {spec!r} (want NAME=HOST:PORT)",
                file=sys.stderr,
            )
            return 1
        if name in clusters:
            print(f"ERROR : duplicate cluster name {name!r}",
                  file=sys.stderr)
            return 1
        clusters[name] = (host_s, int(port_s))
    if not clusters:
        print("ERROR : at least one -cluster NAME=HOST:PORT is required",
              file=sys.stderr)
        return 1
    from kubernetesclustercapacity_tpu.telemetry.metrics import REGISTRY
    from kubernetesclustercapacity_tpu.telemetry.process import (
        register_process_metrics,
    )
    from kubernetesclustercapacity_tpu.telemetry.tracectx import (
        parse_sample_spec,
    )

    try:
        parse_sample_spec(args.trace_sample)
    except ValueError as e:
        print(f"ERROR : {e}", file=sys.stderr)
        return 1
    trace_log = None
    if args.trace_log:
        from kubernetesclustercapacity_tpu.telemetry.tracing import (
            TraceLog,
        )

        trace_log = TraceLog(
            args.trace_log, max_bytes=args.trace_log_max_bytes
        )
    register_process_metrics(REGISTRY)

    try:
        fed = FederationServer(
            clusters,
            host=args.host,
            port=args.port,
            stale_after_s=args.fed_stale_after_s,
            evict_after_s=args.fed_evict_after_s,
            auth_token=auth_token,
            plane_token=auth_token,
            registry=REGISTRY,
            trace_log=trace_log,
            trace_sample=args.trace_sample,
        )
    except (OSError, ValueError, FederationError) as e:
        print(f"ERROR : {e}", file=sys.stderr)
        return 1
    metrics_server = None
    if args.metrics_port:
        from kubernetesclustercapacity_tpu.telemetry.exposition import (
            start_metrics_server,
        )

        try:
            metrics_server = start_metrics_server(
                REGISTRY,
                host=args.host,
                port=args.metrics_port,
                healthy=fed.healthy,
                status=lambda: {"federation": fed.status()},
            )
        except OSError as e:
            print(f"ERROR : cannot bind metrics port: {e}", file=sys.stderr)
            fed.close()
            return 1
        print(
            f"metrics on http://{metrics_server.address[0]}:"
            f"{metrics_server.address[1]}/metrics",
            file=sys.stderr,
        )
    print(
        f"federating {len(clusters)} cluster(s) on "
        f"{fed.address[0]}:{fed.address[1]} "
        f"(stale>{fed.stale_after_s:g}s, lost>{fed.evict_after_s:g}s)",
        file=sys.stderr,
    )
    try:
        fed.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        if metrics_server is not None:
            metrics_server.shutdown()
        fed.close()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
