"""Synthetic cluster fixture generation — the framework's fake-cluster backend.

The reference can only run against a live apiserver (SURVEY.md §4: it has no
tests, no fixtures, no fake clientset).  This module is the new framework's
replacement: deterministic, seedable generators of node/pod fixtures in the
oracle's schema (see :mod:`kubernetesclustercapacity_tpu.oracle.reference`),
shaped like what a real kubelet reports (memory in ``Ki``, the legacy
5-condition layout, ~110-pod capacity), so no cluster is ever needed.

Scales to the BASELINE.json evaluation ladder: config 1 is the checked-in
3-node kind-style JSON under ``tests/fixtures/``; configs 2-3 use
:func:`synthetic_fixture` at 1k / 10k nodes.
"""

from __future__ import annotations

import json

__all__ = ["synthetic_fixture", "synthetic_multi_workload", "load_fixture", "save_fixture"]

# Legacy 5-condition layout the reference's health check hardcodes
# (SURVEY.md §2.2 C3): the first four must be "False" for a node to count.
_CONDITION_TYPES = (
    "OutOfDisk",
    "MemoryPressure",
    "DiskPressure",
    "PIDPressure",
    "Ready",
)

_CPU_CORES_CHOICES = (2, 4, 8, 16, 32, 64)
_CONTAINER_CPU_REQ = ("50m", "100m", "250m", "500m", "1", "2")
_CONTAINER_MEM_REQ = ("64Mi", "128Mi", "256Mi", "512Mi", "1Gi", "2Gi")


def synthetic_fixture(
    n_nodes: int,
    *,
    seed: int = 0,
    pods_per_node: int = 12,
    unhealthy_frac: float = 0.05,
    unparseable_mem_frac: float = 0.02,
    unscheduled_running_pods: int = 0,
    taint_frac: float = 0.0,
    topology: tuple[int, int] | None = None,
) -> dict:
    """Generate a deterministic fixture of ``n_nodes`` nodes and their pods.

    * ``unhealthy_frac`` of nodes get a pressure condition ``"True"`` → the
      reference health check skips them, leaving phantom zero-nodes (Q4).
    * ``unparseable_mem_frac`` of nodes advertise memory as ``"<n>Gi"`` —
      which ``bytefmt`` rejects, zeroing that node's memory (Q5).
    * ``unscheduled_running_pods`` adds Running pods with an empty
      ``nodeName`` — these bind to phantom nodes through the degenerate field
      selector (Q4).
    * ``taint_frac`` of nodes carry a NoSchedule taint (used by the
      constraint-mask layer; invisible to reference semantics).
    * ``topology=(zones, racks_per_zone)`` labels every node with the
      well-known ``topology.kubernetes.io/{zone,rack}`` keys,
      round-robin over ``zones * racks_per_zone`` racks.  Rack label
      VALUES repeat across zones (``r0`` exists in every zone) on
      purpose — the topology model must nest them into distinct
      domains.  Assignment is columnar (two numpy gathers feeding the
      existing per-node dict literal), so hierarchical 1M-node fleets
      build without any new per-node Python work.

    Pod phases are mostly Running with a sprinkle of every excluded phase, so
    the Running-only field-selector semantics (Q7) are exercised.

    .. note:: The returned fixture ALIASES mutable objects: one shared
       container dict per distinct request shape, one shared containers
       LIST per distinct per-pod shape combination, one shared
       initContainers list, and one shared conditions list for all healthy
       nodes (a few dozen objects serve ~100k containers — this is where
       the generator's speed comes from).  Treat fixtures as immutable
       JSON-shaped data, as every framework consumer does; to tweak one
       pod in place, ``json.loads(json.dumps(fx))`` first (or replace
       whole containers/conditions values rather than mutating them).
       Per-node dicts (``allocatable``, ``labels``, ``taints``) are NOT
       shared.
    """
    # All randomness is pre-drawn as numpy arrays (one generator call per
    # decision KIND, not per object), per-container attributes collapse to
    # ONE integer shape code via numpy column math, every repeated
    # sub-object (container dicts, per-pod container lists, conditions)
    # is interned, and the per-pod columns (names, node names, phases,
    # namespaces, container lists) are assembled as whole columns —
    # object-array gathers and C-level repeats — so the only per-pod
    # Python bytecode left is one dict literal in a zip comprehension.
    # Same schema and distributions; per-seed VALUES differ from earlier
    # generator versions (tests compare paths on the same fixture, never
    # absolute contents).
    import gc

    import numpy as np

    rng = np.random.default_rng(seed)
    nodes = []

    cores_all = rng.choice(np.asarray(_CPU_CORES_CHOICES), size=n_nodes)
    mem_slack = rng.integers(0, 2**18, size=n_nodes)
    unhealthy_all = rng.random(n_nodes) < unhealthy_frac
    unhealthy_cond = rng.integers(0, 4, size=n_nodes)
    unparseable_all = rng.random(n_nodes) < unparseable_mem_frac
    tainted_all = rng.random(n_nodes) < taint_frac
    pods_per = rng.integers(0, pods_per_node * 2, size=n_nodes)

    n_pods = int(pods_per.sum()) + unscheduled_running_pods
    _PHASES = ("Running", "Pending", "Succeeded", "Failed", "Unknown")
    phase_idx = rng.choice(
        np.arange(len(_PHASES)),
        size=n_pods,
        p=np.asarray((88, 4, 4, 2, 2)) / 100.0,
    )
    _NAMESPACES = ("default", "kube-system", "batch", "web")
    ns_idx = rng.choice(np.arange(len(_NAMESPACES)), size=n_pods)
    n_containers = rng.choice(
        np.asarray((1, 2, 3)), size=n_pods, p=np.asarray((0.7, 0.2, 0.1))
    )
    has_init = rng.random(n_pods) < 0.1
    n_total_containers = int(n_containers.sum())
    has_req = rng.random(n_total_containers) < 0.9
    has_lim = rng.random(n_total_containers) < 0.7
    cpu_idx = rng.integers(0, len(_CONTAINER_CPU_REQ), size=n_total_containers)
    mem_idx = rng.integers(0, len(_CONTAINER_MEM_REQ), size=n_total_containers)

    # One integer code per container: (cpu, mem, has_lim) collapsed, -1
    # for the no-requests shape — then one integer COMBO per pod (its
    # containers' codes base-shifted into a single int), all as numpy
    # column math.  Container dicts intern per code, containers LISTS
    # intern per combo (a cluster has few distinct request shapes, so
    # both LUTs stay tiny).
    n_mem = len(_CONTAINER_MEM_REQ)
    codes = np.where(
        has_req, (cpu_idx * n_mem + mem_idx) * 2 + has_lim, -1
    ).astype(np.int64)
    container_lut: dict[int, dict] = {}
    for code in np.unique(codes).tolist():
        if code < 0:
            container_lut[code] = {"resources": {}}
            continue
        lim = code % 2
        cpu = _CONTAINER_CPU_REQ[code // 2 // n_mem]
        mem = _CONTAINER_MEM_REQ[code // 2 % n_mem]
        resources = {"requests": {"cpu": cpu, "memory": mem}}
        if lim:
            resources["limits"] = {"cpu": cpu, "memory": mem}
        container_lut[code] = {"resources": resources}

    starts = np.zeros(n_pods, dtype=np.int64)
    if n_pods > 1:
        np.cumsum(n_containers[:-1], out=starts[1:])
    base = 2 * len(_CONTAINER_CPU_REQ) * n_mem + 2  # codes span [-1, base-3]
    combo = codes[starts] + 2
    if n_pods:
        # Second/third container codes (index wraps harmlessly for pods
        # that don't have one — the where() discards the gathered value).
        wrap = max(n_total_containers, 1)
        second = np.where(
            n_containers >= 2, codes[(starts + 1) % wrap] + 2, 0
        )
        third = np.where(
            n_containers >= 3, codes[(starts + 2) % wrap] + 2, 0
        )
        combo = combo + base * second + base * base * third
    combo = combo.astype(np.int32)  # base**3 < 2^31: cheaper unique sort
    clist_lut: dict[int, list] = {}
    for cb in np.unique(combo).tolist():
        # The combo int IS the container-code sequence (base-shifted), so
        # each distinct list decodes straight from the key.
        c0, rest = cb % base - 2, cb // base
        lst = [container_lut[c0]]
        while rest:
            lst.append(container_lut[rest % base - 2])
            rest //= base
        clist_lut[cb] = lst

    _init_containers = [
        {"resources": {"requests": {"cpu": "1", "memory": "1Gi"}}}
    ]

    # Python lists for the remaining per-object reads: numpy scalar
    # extraction costs ~100 ns per index, which at ~500k reads would give
    # back most of the vectorization win.  String columns gather through
    # object arrays (C-level pointer copies, no per-element formatting).
    mem_kib_col = (
        cores_all.astype(np.int64) * (4 * 1024 * 1024) - mem_slack
    ).tolist()
    unhealthy_idx = np.flatnonzero(unhealthy_all).tolist()
    cores_all = cores_all.tolist()
    unhealthy_cond = unhealthy_cond.tolist()
    unparseable_all = unparseable_all.tolist()
    tainted_all = tainted_all.tolist()
    pods_per_l = pods_per.tolist()
    phases = np.asarray(_PHASES, dtype=object)[phase_idx].tolist()
    namespaces = np.asarray(_NAMESPACES, dtype=object)[ns_idx].tolist()

    # Pod-name suffix table: "-000", "-001", ... built once (pods_per is
    # bounded by 2*pods_per_node), so a pod name is prefix + table slot.
    max_per = max(pods_per_l, default=0)
    suffixes = [f"-{j:03d}" for j in range(max_per)]

    # One shared conditions list serves every healthy node (same interning
    # rationale as containers); unhealthy nodes build their own copy since
    # one entry differs.
    _healthy_conditions = [
        {"type": t, "status": "False"} for t in _CONDITION_TYPES[:4]
    ] + [{"type": "Ready", "status": "True"}]
    _zones = ("zone-0", "zone-1", "zone-2")
    _cores_str = {c: str(c) for c in _CPU_CORES_CHOICES}
    _taint = {"key": "dedicated", "value": "batch", "effect": "NoSchedule"}

    # Topology label columns (interned string tables gathered through
    # object arrays — the same columnar technique as every other column).
    topo_col: list = [None] * n_nodes
    if topology is not None:
        t_zones, racks_per = topology
        if t_zones < 1 or racks_per < 1:
            raise ValueError(
                f"topology wants (zones >= 1, racks_per_zone >= 1), "
                f"got {topology!r}"
            )
        n_racks = t_zones * racks_per
        rack_idx = np.arange(n_nodes) % n_racks
        zone_tbl = np.asarray(
            [f"tz-{z}" for z in range(t_zones)], dtype=object
        )
        rack_tbl = np.asarray(
            [f"r{r}" for r in range(racks_per)], dtype=object
        )
        # One interned {zone, rack} label-pair dict per rack: n_racks
        # distinct dicts serve all N nodes.
        pair_tbl = np.asarray(
            [
                {
                    "topology.kubernetes.io/zone": zone_tbl[r // racks_per],
                    "topology.kubernetes.io/rack": rack_tbl[r % racks_per],
                }
                for r in range(n_racks)
            ],
            dtype=object,
        )
        topo_col = pair_tbl[rack_idx].tolist()
    _no_topo: dict = {}

    # The bulk-assembly phase allocates ~N + ΣP acyclic dicts; pausing the
    # cyclic GC for it avoids ~500 young-generation scans over an
    # ever-growing live set (the objects survive anyway — nothing here is
    # garbage until the fixture itself is).
    gc_was_enabled = gc.isenabled()
    gc.disable()
    try:
        node_names = [f"node-{i:05d}" for i in range(n_nodes)]
        # Kubelet-style memory: a little less than the round GiB figure,
        # in Ki — except the unparseable fraction, which advertises "Gi"
        # (bytefmt rejects it, Q5).
        mem_strs = [
            f"{m // 1024**2}Gi" if bad else f"{m}Ki"
            for m, bad in zip(mem_kib_col, unparseable_all)
        ]
        # Shared conditions column; only the unhealthy minority builds its
        # own copy (one entry differs).
        conds_col = [_healthy_conditions] * n_nodes
        for i in unhealthy_idx:
            conditions = [dict(c) for c in _healthy_conditions]
            conditions[unhealthy_cond[i]]["status"] = "True"
            conds_col[i] = conditions
        n_range = range(n_nodes)
        nodes = [
            {
                "name": nm,
                "allocatable": {
                    "cpu": _cores_str[cores],
                    "memory": ms,
                    "pods": "110",
                },
                "conditions": cd,
                "labels": {
                    "kubernetes.io/hostname": nm,
                    "zone": _zones[i % 3],
                    "pool": "default" if i % 4 else "highmem",
                    **(_no_topo if tp is None else tp),
                },
                "taints": [_taint.copy()] if tn else [],
            }
            for i, nm, cores, ms, cd, tn, tp in zip(
                n_range, node_names, cores_all, mem_strs, conds_col,
                tainted_all, topo_col,
            )
        ]

        # -- pod columns, then one zip comprehension ---------------------
        n_scheduled = n_pods - unscheduled_running_pods
        pod_names = [
            pfx + sfx
            for pfx, k in zip(node_names, pods_per_l)
            for sfx in suffixes[:k]
        ]
        pod_names.extend(
            f"orphan-{k:03d}" for k in range(unscheduled_running_pods)
        )
        node_of_pod = np.repeat(
            np.asarray(node_names, dtype=object), pods_per
        ).tolist()
        # Orphans bind to phantom nodes through the empty nodeName (Q4)
        # and must be Running regardless of the pre-drawn phase.
        node_of_pod.extend([""] * unscheduled_running_pods)
        phases[n_scheduled:] = ["Running"] * unscheduled_running_pods
        clists = [clist_lut[cb] for cb in combo.tolist()]
        pods = [
            {
                "name": nm,
                "namespace": ns,
                "nodeName": nn,
                "phase": ph,
                "containers": cl,
            }
            for nm, ns, nn, ph, cl in zip(
                pod_names, namespaces, node_of_pod, phases, clists
            )
        ]
        for p in np.flatnonzero(has_init).tolist():
            # Init containers exist but must be ignored by reference (Q7).
            pods[p]["initContainers"] = _init_containers
    finally:
        if gc_was_enabled:
            gc.enable()

    return {"nodes": nodes, "pods": pods}


def load_fixture(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def save_fixture(fixture: dict, path: str) -> None:
    with open(path, "w") as f:
        json.dump(fixture, f, indent=1)


def synthetic_multi_workload(snap, n_scenarios: int, *, seed: int = 0):
    """A 3-resource (cpu, memory, GPU-count) R-dim workload over ``snap``.

    Returns ``(alloc_rn, used_rn, reqs_sr, replicas)``: the ``[3, N]``
    resource matrix (GPU allocatables drawn 0-8, none used), an ``[S, 3]``
    request grid whose GPU column includes zeros ("does not consume"),
    and the ``[S]`` replica targets.
    One definition serves every R-dim surface's tests/dry-runs so the
    config-4 resource layout cannot drift between them.
    """
    import numpy as np

    from kubernetesclustercapacity_tpu.scenario import random_scenario_grid

    rng = np.random.default_rng(seed)
    n = snap.n_nodes
    alloc_rn = np.stack(
        [snap.alloc_cpu_milli, snap.alloc_mem_bytes,
         rng.integers(0, 9, n)]
    )
    used_rn = np.stack(
        [snap.used_cpu_req_milli, snap.used_mem_req_bytes,
         np.zeros(n, dtype=np.int64)]
    )
    grid = random_scenario_grid(n_scenarios, seed=seed + 1)
    reqs_sr = np.stack(
        [grid.cpu_request_milli, grid.mem_request_bytes,
         rng.integers(0, 3, n_scenarios)],
        axis=1,
    ).astype(np.int64)
    return alloc_rn, used_rn, reqs_sr, grid.replicas
