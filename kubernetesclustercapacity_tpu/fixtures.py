"""Synthetic cluster fixture generation — the framework's fake-cluster backend.

The reference can only run against a live apiserver (SURVEY.md §4: it has no
tests, no fixtures, no fake clientset).  This module is the new framework's
replacement: deterministic, seedable generators of node/pod fixtures in the
oracle's schema (see :mod:`kubernetesclustercapacity_tpu.oracle.reference`),
shaped like what a real kubelet reports (memory in ``Ki``, the legacy
5-condition layout, ~110-pod capacity), so no cluster is ever needed.

Scales to the BASELINE.json evaluation ladder: config 1 is the checked-in
3-node kind-style JSON under ``tests/fixtures/``; configs 2-3 use
:func:`synthetic_fixture` at 1k / 10k nodes.
"""

from __future__ import annotations

import json

__all__ = ["synthetic_fixture", "synthetic_multi_workload", "load_fixture", "save_fixture"]

# Legacy 5-condition layout the reference's health check hardcodes
# (SURVEY.md §2.2 C3): the first four must be "False" for a node to count.
_CONDITION_TYPES = (
    "OutOfDisk",
    "MemoryPressure",
    "DiskPressure",
    "PIDPressure",
    "Ready",
)

_CPU_CORES_CHOICES = (2, 4, 8, 16, 32, 64)
_CONTAINER_CPU_REQ = ("50m", "100m", "250m", "500m", "1", "2")
_CONTAINER_MEM_REQ = ("64Mi", "128Mi", "256Mi", "512Mi", "1Gi", "2Gi")


def synthetic_fixture(
    n_nodes: int,
    *,
    seed: int = 0,
    pods_per_node: int = 12,
    unhealthy_frac: float = 0.05,
    unparseable_mem_frac: float = 0.02,
    unscheduled_running_pods: int = 0,
    taint_frac: float = 0.0,
) -> dict:
    """Generate a deterministic fixture of ``n_nodes`` nodes and their pods.

    * ``unhealthy_frac`` of nodes get a pressure condition ``"True"`` → the
      reference health check skips them, leaving phantom zero-nodes (Q4).
    * ``unparseable_mem_frac`` of nodes advertise memory as ``"<n>Gi"`` —
      which ``bytefmt`` rejects, zeroing that node's memory (Q5).
    * ``unscheduled_running_pods`` adds Running pods with an empty
      ``nodeName`` — these bind to phantom nodes through the degenerate field
      selector (Q4).
    * ``taint_frac`` of nodes carry a NoSchedule taint (used by the
      constraint-mask layer; invisible to reference semantics).

    Pod phases are mostly Running with a sprinkle of every excluded phase, so
    the Running-only field-selector semantics (Q7) are exercised.

    .. note:: The returned fixture ALIASES mutable objects: one shared
       container dict per distinct request shape, one shared initContainers
       list, and one shared conditions list for all healthy nodes (a few
       dozen objects serve ~100k containers — this is where the generator's
       speed comes from).  Treat fixtures as immutable JSON-shaped data, as
       every framework consumer does; to tweak one pod in place,
       ``json.loads(json.dumps(fx))`` first (or replace whole
       containers/conditions values rather than mutating them).  Per-node
       dicts (``allocatable``, ``labels``, ``taints``) are NOT shared.
    """
    # All randomness is pre-drawn as numpy arrays (one generator call per
    # decision KIND, not per object) — at 10k nodes / ~115k pods the old
    # per-object random.choice walk was ~2.4 s of pure draw overhead; the
    # remaining cost is dict assembly.  Same schema and distributions;
    # per-seed VALUES differ from the pre-vectorization generator (tests
    # compare paths on the same fixture, never absolute contents).
    import numpy as np

    rng = np.random.default_rng(seed)
    nodes = []
    pods = []

    cores_all = rng.choice(np.asarray(_CPU_CORES_CHOICES), size=n_nodes)
    mem_slack = rng.integers(0, 2**18, size=n_nodes)
    unhealthy_all = rng.random(n_nodes) < unhealthy_frac
    unhealthy_cond = rng.integers(0, 4, size=n_nodes)
    unparseable_all = rng.random(n_nodes) < unparseable_mem_frac
    tainted_all = rng.random(n_nodes) < taint_frac
    pods_per = rng.integers(0, pods_per_node * 2, size=n_nodes)

    n_pods = int(pods_per.sum()) + unscheduled_running_pods
    phases = rng.choice(
        np.asarray(("Running", "Pending", "Succeeded", "Failed", "Unknown")),
        size=n_pods,
        p=np.asarray((88, 4, 4, 2, 2)) / 100.0,
    )
    namespaces = rng.choice(
        np.asarray(("default", "kube-system", "batch", "web")), size=n_pods
    )
    n_containers = rng.choice(
        np.asarray((1, 2, 3)), size=n_pods, p=np.asarray((0.7, 0.2, 0.1))
    )
    has_init = rng.random(n_pods) < 0.1
    n_total_containers = int(n_containers.sum())
    has_req = rng.random(n_total_containers) < 0.9
    has_lim = rng.random(n_total_containers) < 0.7
    cpu_reqs = rng.choice(
        np.asarray(_CONTAINER_CPU_REQ), size=n_total_containers
    )
    mem_reqs = rng.choice(
        np.asarray(_CONTAINER_MEM_REQ), size=n_total_containers
    )

    # Python lists for the per-object reads: numpy scalar extraction costs
    # ~100 ns per index, which at ~500k reads would give back most of the
    # vectorization win.
    cores_all = cores_all.tolist()
    mem_slack = mem_slack.tolist()
    unhealthy_all = unhealthy_all.tolist()
    unhealthy_cond = unhealthy_cond.tolist()
    unparseable_all = unparseable_all.tolist()
    tainted_all = tainted_all.tolist()
    pods_per = pods_per.tolist()
    phases = phases.tolist()
    namespaces = namespaces.tolist()
    n_containers = n_containers.tolist()
    has_init = has_init.tolist()
    has_req = has_req.tolist()
    has_lim = has_lim.tolist()
    cpu_reqs = cpu_reqs.tolist()
    mem_reqs = mem_reqs.tolist()

    pid = cid = 0

    # Container dicts are INTERNED: the distinct (cpu, mem, has_lim) shapes
    # number a few dozen, so each shape is built once and the same object is
    # shared by every container with that shape (and likewise the one
    # no-requests container and the one init-container list).  Fixtures are
    # read-only JSON-shaped data everywhere downstream (packers, oracle,
    # store — event updates build NEW dicts; the store deep-copies on
    # ingestion), so sharing is safe and ``json.dump`` serializes it
    # identically to the unshared equivalent.  See the docstring note.
    _container_lut: dict = {}

    def make_container(ci: int) -> dict:
        if not has_req[ci]:  # some containers set no requests at all
            key = None
        else:
            key = (cpu_reqs[ci], mem_reqs[ci], has_lim[ci])
        c = _container_lut.get(key)
        if c is None:
            resources: dict = {}
            if key is not None:
                cpu, mem, lim = key
                resources["requests"] = {"cpu": cpu, "memory": mem}
                if lim:
                    resources["limits"] = {"cpu": cpu, "memory": mem}
            c = _container_lut[key] = {"resources": resources}
        return c

    _init_containers = [
        {"resources": {"requests": {"cpu": "1", "memory": "1Gi"}}}
    ]

    def make_pod(name: str, node_name: str) -> dict:
        nonlocal pid, cid
        containers = []
        for _ in range(n_containers[pid]):
            containers.append(make_container(cid))
            cid += 1
        pod = {
            "name": name,
            "namespace": namespaces[pid],
            "nodeName": node_name,
            "phase": phases[pid],
            "containers": containers,
        }
        if has_init[pid]:  # init containers exist but must be ignored (Q7)
            pod["initContainers"] = _init_containers
        pid += 1
        return pod

    # One shared conditions list serves every healthy node (same interning
    # rationale as containers); unhealthy nodes build their own copy since
    # one entry differs.
    _healthy_conditions = [
        {"type": t, "status": "False"} for t in _CONDITION_TYPES[:4]
    ] + [{"type": "Ready", "status": "True"}]

    for i in range(n_nodes):
        name = f"node-{i:05d}"
        cores = cores_all[i]
        # Kubelet-style: a little less than the round GiB figure, in Ki.
        mem_kib = cores * 4 * 1024 * 1024 - mem_slack[i]

        if unhealthy_all[i]:
            conditions = [dict(c) for c in _healthy_conditions]
            conditions[unhealthy_cond[i]]["status"] = "True"
        else:
            conditions = _healthy_conditions

        node = {
            "name": name,
            "allocatable": {
                "cpu": str(cores),
                "memory": (
                    f"{mem_kib // 1024**2}Gi"
                    if unparseable_all[i]
                    else f"{mem_kib}Ki"
                ),
                "pods": "110",
            },
            "conditions": conditions,
            "labels": {
                "kubernetes.io/hostname": name,
                "zone": f"zone-{i % 3}",
                "pool": "default" if i % 4 else "highmem",
            },
            "taints": [],
        }
        if tainted_all[i]:
            node["taints"].append(
                {"key": "dedicated", "value": "batch", "effect": "NoSchedule"}
            )
        nodes.append(node)

        for j in range(pods_per[i]):
            pods.append(make_pod(f"pod-{i:05d}-{j:03d}", name))

    for k in range(unscheduled_running_pods):
        orphan = make_pod(f"orphan-{k:03d}", "")
        # Orphans must be Running (they exist to exercise the phantom-node
        # matching), regardless of the pre-drawn phase.
        orphan["phase"] = "Running"
        pods.append(orphan)

    return {"nodes": nodes, "pods": pods}


def load_fixture(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def save_fixture(fixture: dict, path: str) -> None:
    with open(path, "w") as f:
        json.dump(fixture, f, indent=1)


def synthetic_multi_workload(snap, n_scenarios: int, *, seed: int = 0):
    """A 3-resource (cpu, memory, GPU-count) R-dim workload over ``snap``.

    Returns ``(alloc_rn, used_rn, reqs_sr, replicas)``: the ``[3, N]``
    resource matrix (GPU allocatables drawn 0-8, none used), an ``[S, 3]``
    request grid whose GPU column includes zeros ("does not consume"),
    and the ``[S]`` replica targets.
    One definition serves every R-dim surface's tests/dry-runs so the
    config-4 resource layout cannot drift between them.
    """
    import numpy as np

    from kubernetesclustercapacity_tpu.scenario import random_scenario_grid

    rng = np.random.default_rng(seed)
    n = snap.n_nodes
    alloc_rn = np.stack(
        [snap.alloc_cpu_milli, snap.alloc_mem_bytes,
         rng.integers(0, 9, n)]
    )
    used_rn = np.stack(
        [snap.used_cpu_req_milli, snap.used_mem_req_bytes,
         np.zeros(n, dtype=np.int64)]
    )
    grid = random_scenario_grid(n_scenarios, seed=seed + 1)
    reqs_sr = np.stack(
        [grid.cpu_request_milli, grid.mem_request_bytes,
         rng.integers(0, 3, n_scenarios)],
        axis=1,
    ).astype(np.int64)
    return alloc_rn, used_rn, reqs_sr, grid.replicas
