"""Lock-discipline checker: guarded fields stay under their lock.

The threaded classes in this codebase (MetricsRegistry, DeviceCache,
MicroBatcher, CapacityTimeline, AuditLog, ...) are safe *by convention*:
each holds a ``self._lock`` and touches its mutable state inside ``with
self._lock:`` blocks.  This rule turns the convention into a check:

1. a class is *threaded* iff it acquires a ``self.<attr>`` lock
   anywhere (``with self._lock:``) or assigns ``threading.Lock/RLock/
   Condition/Semaphore`` to an attribute in ``__init__``;
2. its *guarded fields* are the ``self.X`` attributes **written** under
   the lock outside ``__init__`` — a field someone mutates under the
   lock is a field every reader must take the lock for;
3. every read or write of a guarded field outside a with-lock block
   (outside ``__init__``, which runs before the object is shared) is a
   finding.

Known-benign escapes use the inline marker — ``# kccap:
lint-ok[lock-discipline] <why the race is acceptable>`` — so every
deliberately racy read is greppable and justified at the site.  Methods
whose bodies run with the lock already held by their caller follow the
``*_locked`` naming convention and are treated as lock-held throughout.

Closures defined inside a method are analyzed as *outside* the lock
even when the ``def`` lexically sits in a ``with`` block: the closure
body runs when called, which is generally after the block exits.

The inference itself — which classes are threaded, which attrs are
their locks, which fields those locks guard — is exposed as
:func:`lock_model` so the *dynamic* sanitizer (:mod:`.sanitize`)
instruments exactly the set the static rule checks: one model, two
provers, cross-checked both directions by ``tests/test_sanitize.py``.
Lock attrs proven by construction in a base class carry into every
subclass (resolved by base name project-wide), so ``class Sub(Base)``
methods acquiring an inherited ``self._mu`` are analyzed too.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from kubernetesclustercapacity_tpu.analysis.callgraph import dotted
from kubernetesclustercapacity_tpu.analysis.engine import Finding, Project

__all__ = ["check", "lock_model", "ClassLockModel", "RULE"]

RULE = "lock-discipline"

_LOCK_CTORS = (
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "threading.Semaphore",
    "threading.BoundedSemaphore",
)


def _self_attr(node) -> str | None:
    """``self.X`` -> ``"X"`` (one level only), else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _lock_items(node) -> set[str]:
    """Lock attrs acquired by this ``with`` statement's items."""
    out: set[str] = set()
    for item in node.items:
        attr = _self_attr(item.context_expr)
        if attr is not None:
            out.add(attr)
    return out


def _is_lock_ctor(call: ast.Call, lock_aliases: set[str]) -> bool:
    path = dotted(call.func)
    if path is None:
        return False
    if path in lock_aliases:
        return True
    # `threading.Lock()` under any module alias for threading.
    tail = path.rsplit(".", 1)[-1]
    return tail in ("Lock", "RLock", "Condition", "Semaphore",
                    "BoundedSemaphore") and "." in path


def _methods(cls: ast.ClassDef):
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _iter_classes(tree: ast.Module):
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            yield node


#: Method names that mutate their receiver in place: calling one on a
#: ``self.X`` container under the lock makes X guarded state exactly
#: like an attribute store would (``self._ring.append(...)``,
#: ``self._pending[key] = ...`` — the attr node's ctx is Load either
#: way, so the scanner must recognize the mutation shapes explicitly).
_MUTATORS = frozenset(
    {
        "append", "appendleft", "add", "clear", "discard", "extend",
        "insert", "move_to_end", "pop", "popitem", "popleft", "remove",
        "setdefault", "update",
    }
)


class _MethodScanner:
    """One pass over a method body tracking whether a self-lock is held
    lexically; collects under-lock writes/reads and out-of-lock
    accesses of candidate fields."""

    def __init__(self, lock_attrs: set[str]) -> None:
        self.lock_attrs = lock_attrs
        self.under_writes: set[str] = set()
        self.accesses: list[tuple[str, bool, bool, ast.AST]] = []
        # (field, is_write, under_lock, node)

    def scan(self, method, *, assume_held: bool) -> None:
        for stmt in method.body:
            self._visit(stmt, assume_held)

    def _container_write(self, node) -> str | None:
        """``self.X[k] = v`` / ``del self.X[k]`` / ``self.X.append(v)``
        -> ``"X"`` when the mutated container is a self attr."""
        if isinstance(node, ast.Subscript) and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            return _self_attr(node.value)
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _MUTATORS
        ):
            return _self_attr(node.func.value)
        return None

    def _visit(self, node, held: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            # Closure bodies run later, when the lock may not be held.
            body = node.body if isinstance(node.body, list) else [node.body]
            for child in body:
                self._visit(child, False)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = _lock_items(node) & self.lock_attrs
            for item in node.items:
                self._visit(item.context_expr, held)
            for child in node.body:
                self._visit(child, held or bool(acquired))
            return
        container = self._container_write(node)
        if (
            container is not None
            and container not in self.lock_attrs
            and held
        ):
            # In-place container mutation under the lock: guards the
            # field (the access itself is recorded when the inner
            # Attribute node is visited below).
            self.under_writes.add(container)
        attr = _self_attr(node)
        if attr is not None and attr not in self.lock_attrs:
            is_write = isinstance(node.ctx, (ast.Store, ast.Del))
            if held and is_write:
                self.under_writes.add(attr)
            self.accesses.append((attr, is_write, held, node))
        for child in ast.iter_child_nodes(node):
            self._visit(child, held)


def _module_lock_aliases(tree: ast.Module) -> set[str]:
    """Lock ctor names visible in this module (e.g. ``from threading
    import Lock``) on top of the canonical dotted forms."""
    lock_aliases: set[str] = set(_LOCK_CTORS)
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "threading":
            for alias in node.names:
                if alias.name in (
                    "Lock", "RLock", "Condition", "Semaphore",
                    "BoundedSemaphore",
                ):
                    lock_aliases.add(alias.asname or alias.name)
    return lock_aliases


def _ctor_proven_attrs(cls: ast.ClassDef, lock_aliases: set[str]) -> set[str]:
    """``self.X = threading.Lock()``-style attrs in this class body."""
    out: set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Call
        ) and _is_lock_ctor(node.value, lock_aliases):
            for tgt in node.targets:
                attr = _self_attr(tgt)
                if attr is not None:
                    out.add(attr)
    return out


@dataclass(frozen=True)
class ClassLockModel:
    """One threaded class as the lock rule understands it."""

    name: str  # class name
    path: str  # repo-relative source path
    lineno: int
    lock_attrs: frozenset  # self attrs that ARE locks
    guarded: frozenset  # self attrs written under a lock outside __init__

    @property
    def key(self) -> tuple[str, str]:
        return (self.path, self.name)


def _class_lock_attrs(
    cls: ast.ClassDef,
    lock_aliases: set[str],
    inherited: set[str],
) -> set[str]:
    """Pass 1: which self attrs are locks in this class?

    ``with self._x:`` where _x is not lock-like (e.g. a client used as
    a context manager) would poison the analysis; keep only
    lock-looking names plus ctor-proven attrs (own or inherited).
    """
    acquired: set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired |= _lock_items(node)
    ctor_proven = _ctor_proven_attrs(cls, lock_aliases)
    proven = {
        a
        for a in acquired
        if "lock" in a.lower() or "cv" in a.lower()
        or "cond" in a.lower() or "sem" in a.lower()
        or a in inherited
    }
    return proven | ctor_proven


def _base_names(cls: ast.ClassDef) -> list[str]:
    """Base-class tail names (``service.server.CapacityServer`` ->
    ``CapacityServer``)."""
    out: list[str] = []
    for b in cls.bases:
        d = dotted(b)
        if d:
            out.append(d.rsplit(".", 1)[-1])
    return out


def _scan_methods(
    cls: ast.ClassDef, lock_attrs: set[str]
) -> dict[str, "_MethodScanner"]:
    scanners: dict[str, _MethodScanner] = {}
    for method in _methods(cls):
        scanner = _MethodScanner(lock_attrs)
        scanner.scan(method, assume_held=method.name.endswith("_locked"))
        scanners[method.name] = scanner
    return scanners


def _guarded_fields(scanners: dict[str, "_MethodScanner"]) -> set[str]:
    """Pass 2: fields written under lock outside __init__."""
    guarded: set[str] = set()
    for name, scanner in scanners.items():
        if name != "__init__":
            guarded |= scanner.under_writes
    return guarded


def _ctor_index(project: Project) -> dict[str, set[str]]:
    """Class name -> ctor-proven lock attrs, project-wide.  Base names
    resolve against this (conservatively by bare name: a subclass in
    another module still inherits its base's proven locks)."""
    index: dict[str, set[str]] = {}
    for src in project.files:
        aliases = _module_lock_aliases(src.tree)
        for cls in _iter_classes(src.tree):
            index.setdefault(cls.name, set()).update(
                _ctor_proven_attrs(cls, aliases)
            )
    return index


def _inherited_attrs(
    cls: ast.ClassDef, ctor_index: dict[str, set[str]]
) -> set[str]:
    out: set[str] = set()
    for base in _base_names(cls):
        out |= ctor_index.get(base, set())
    return out


def lock_model(project: Project) -> dict[tuple[str, str], ClassLockModel]:
    """Threaded-class inference as data: ``(path, class) -> model``.

    This is the single source of truth the static rule checks and the
    dynamic sanitizer instruments — the two provers cannot drift apart
    because they consume the same inference.
    """
    out: dict[tuple[str, str], ClassLockModel] = {}
    ctor_index = _ctor_index(project)
    for src in project.files:
        lock_aliases = _module_lock_aliases(src.tree)
        for cls in _iter_classes(src.tree):
            lock_attrs = _class_lock_attrs(
                cls, lock_aliases, _inherited_attrs(cls, ctor_index)
            )
            if not lock_attrs:
                continue
            guarded = _guarded_fields(_scan_methods(cls, lock_attrs))
            m = ClassLockModel(
                name=cls.name,
                path=src.rel_path,
                lineno=cls.lineno,
                lock_attrs=frozenset(lock_attrs),
                guarded=frozenset(guarded),
            )
            out[m.key] = m
    return out


def check(project: Project):
    findings: list[Finding] = []
    ctor_index = _ctor_index(project)
    for src in project.files:
        lock_aliases = _module_lock_aliases(src.tree)
        for cls in _iter_classes(src.tree):
            lock_attrs = _class_lock_attrs(
                cls, lock_aliases, _inherited_attrs(cls, ctor_index)
            )
            if not lock_attrs:
                continue
            scanners = _scan_methods(cls, lock_attrs)
            guarded = _guarded_fields(scanners)
            if not guarded:
                continue

            # -- pass 3: out-of-lock accesses of guarded fields.
            for name, scanner in scanners.items():
                if name == "__init__":
                    continue
                for field, is_write, held, node in scanner.accesses:
                    if held or field not in guarded:
                        continue
                    verb = "write to" if is_write else "read of"
                    findings.append(
                        Finding(
                            rule=RULE,
                            severity="error",
                            path=src.rel_path,
                            line=node.lineno,
                            col=node.col_offset,
                            message=(
                                f"unguarded {verb} `self.{field}` in "
                                f"`{cls.name}.{name}` — the field is "
                                f"mutated under `self.{sorted(lock_attrs)[0]}`"
                                " elsewhere, so lock-free access races"
                            ),
                            symbol=f"{cls.name}.{field}@{name}",
                        )
                    )
    return findings
