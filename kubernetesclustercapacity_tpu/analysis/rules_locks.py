"""Lock-discipline checker: guarded fields stay under their lock.

The threaded classes in this codebase (MetricsRegistry, DeviceCache,
MicroBatcher, CapacityTimeline, AuditLog, ...) are safe *by convention*:
each holds a ``self._lock`` and touches its mutable state inside ``with
self._lock:`` blocks.  This rule turns the convention into a check:

1. a class is *threaded* iff it acquires a ``self.<attr>`` lock
   anywhere (``with self._lock:``) or assigns ``threading.Lock/RLock/
   Condition/Semaphore`` to an attribute in ``__init__``;
2. its *guarded fields* are the ``self.X`` attributes **written** under
   the lock outside ``__init__`` — a field someone mutates under the
   lock is a field every reader must take the lock for;
3. every read or write of a guarded field outside a with-lock block
   (outside ``__init__``, which runs before the object is shared) is a
   finding.

Known-benign escapes use the inline marker — ``# kccap:
lint-ok[lock-discipline] <why the race is acceptable>`` — so every
deliberately racy read is greppable and justified at the site.  Methods
whose bodies run with the lock already held by their caller follow the
``*_locked`` naming convention and are treated as lock-held throughout.

Closures defined inside a method are analyzed as *outside* the lock
even when the ``def`` lexically sits in a ``with`` block: the closure
body runs when called, which is generally after the block exits.
"""

from __future__ import annotations

import ast

from kubernetesclustercapacity_tpu.analysis.callgraph import dotted
from kubernetesclustercapacity_tpu.analysis.engine import Finding, Project

__all__ = ["check", "RULE"]

RULE = "lock-discipline"

_LOCK_CTORS = (
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "threading.Semaphore",
    "threading.BoundedSemaphore",
)


def _self_attr(node) -> str | None:
    """``self.X`` -> ``"X"`` (one level only), else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _lock_items(node) -> set[str]:
    """Lock attrs acquired by this ``with`` statement's items."""
    out: set[str] = set()
    for item in node.items:
        attr = _self_attr(item.context_expr)
        if attr is not None:
            out.add(attr)
    return out


def _is_lock_ctor(call: ast.Call, lock_aliases: set[str]) -> bool:
    path = dotted(call.func)
    if path is None:
        return False
    if path in lock_aliases:
        return True
    # `threading.Lock()` under any module alias for threading.
    tail = path.rsplit(".", 1)[-1]
    return tail in ("Lock", "RLock", "Condition", "Semaphore",
                    "BoundedSemaphore") and "." in path


def _methods(cls: ast.ClassDef):
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _iter_classes(tree: ast.Module):
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            yield node


class _MethodScanner:
    """One pass over a method body tracking whether a self-lock is held
    lexically; collects under-lock writes/reads and out-of-lock
    accesses of candidate fields."""

    def __init__(self, lock_attrs: set[str]) -> None:
        self.lock_attrs = lock_attrs
        self.under_writes: set[str] = set()
        self.accesses: list[tuple[str, bool, bool, ast.AST]] = []
        # (field, is_write, under_lock, node)

    def scan(self, method, *, assume_held: bool) -> None:
        for stmt in method.body:
            self._visit(stmt, assume_held)

    def _visit(self, node, held: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            # Closure bodies run later, when the lock may not be held.
            body = node.body if isinstance(node.body, list) else [node.body]
            for child in body:
                self._visit(child, False)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = _lock_items(node) & self.lock_attrs
            for item in node.items:
                self._visit(item.context_expr, held)
            for child in node.body:
                self._visit(child, held or bool(acquired))
            return
        attr = _self_attr(node)
        if attr is not None and attr not in self.lock_attrs:
            is_write = isinstance(node.ctx, (ast.Store, ast.Del))
            if held and is_write:
                self.under_writes.add(attr)
            self.accesses.append((attr, is_write, held, node))
        for child in ast.iter_child_nodes(node):
            self._visit(child, held)


def check(project: Project):
    findings: list[Finding] = []
    for src in project.files:
        # Module-level lock ctor aliases (e.g. `from threading import Lock`).
        lock_aliases: set[str] = set(_LOCK_CTORS)
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ImportFrom) and node.module == "threading":
                for alias in node.names:
                    if alias.name in (
                        "Lock", "RLock", "Condition", "Semaphore",
                        "BoundedSemaphore",
                    ):
                        lock_aliases.add(alias.asname or alias.name)

        for cls in _iter_classes(src.tree):
            # -- pass 1: which attrs are locks?
            lock_attrs: set[str] = set()
            for node in ast.walk(cls):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    lock_attrs |= _lock_items(node)
                elif isinstance(node, ast.Assign):
                    if isinstance(node.value, ast.Call) and _is_lock_ctor(
                        node.value, lock_aliases
                    ):
                        for tgt in node.targets:
                            attr = _self_attr(tgt)
                            if attr is not None:
                                lock_attrs.add(attr)
            # `with self._x:` where _x is not lock-like (e.g. a client
            # used as a context manager) would poison the analysis; keep
            # only lock-looking names plus ctor-proven attrs.
            proven = {
                a
                for a in lock_attrs
                if "lock" in a.lower() or "cv" in a.lower()
                or "cond" in a.lower() or "sem" in a.lower()
            }
            ctor_proven = set()
            for node in ast.walk(cls):
                if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call
                ) and _is_lock_ctor(node.value, lock_aliases):
                    for tgt in node.targets:
                        attr = _self_attr(tgt)
                        if attr is not None:
                            ctor_proven.add(attr)
            lock_attrs = proven | ctor_proven
            if not lock_attrs:
                continue

            # -- pass 2: guarded set = fields written under lock outside
            # __init__ (per-method scanners, then union).
            scanners: dict[str, _MethodScanner] = {}
            for method in _methods(cls):
                scanner = _MethodScanner(lock_attrs)
                scanner.scan(
                    method,
                    assume_held=method.name.endswith("_locked"),
                )
                scanners[method.name] = scanner
            guarded: set[str] = set()
            for name, scanner in scanners.items():
                if name != "__init__":
                    guarded |= scanner.under_writes

            if not guarded:
                continue

            # -- pass 3: out-of-lock accesses of guarded fields.
            for name, scanner in scanners.items():
                if name == "__init__":
                    continue
                for field, is_write, held, node in scanner.accesses:
                    if held or field not in guarded:
                        continue
                    verb = "write to" if is_write else "read of"
                    findings.append(
                        Finding(
                            rule=RULE,
                            severity="error",
                            path=src.rel_path,
                            line=node.lineno,
                            col=node.col_offset,
                            message=(
                                f"unguarded {verb} `self.{field}` in "
                                f"`{cls.name}.{name}` — the field is "
                                f"mutated under `self.{sorted(lock_attrs)[0]}`"
                                " elsewhere, so lock-free access races"
                            ),
                            symbol=f"{cls.name}.{field}@{name}",
                        )
                    )
    return findings
