"""Jit-purity prover: nothing host-side is reachable from a traced region.

``KCCAP_TELEMETRY=0`` promises *zero registry calls in jitted code*
(PR 2), and the whole serving stack assumes jitted functions never take
locks, never touch wall clocks, and never coerce traced arrays to
Python scalars (each coercion is a device sync; under ``vmap`` it is an
error).  This rule makes those promises theorems: build the intra-
package call graph rooted at every jit/pjit/pallas function
(:mod:`.callgraph`), then flag — at the offending call site, with the
root→...→callee chain in the message — anything in these categories:

* ``host-subsystem`` — a call edge into a host-side subsystem
  (telemetry, devcache, service, audit, timeline, resilience, ...);
* ``lock`` — ``with self._lock``-style acquisition, ``.acquire()``, or
  ``threading.*`` construction;
* ``io`` — ``open``/``print``/``input``, ``os.environ``/``os.getenv``;
* ``clock`` / ``random`` — stdlib ``time.*`` / ``random.*`` (NOT
  ``jax.random``/``numpy.random``, which resolve differently);
* ``host-callback`` — ``jax.pure_callback``/``io_callback``/
  ``jax.debug.print`` and friends (escape hatches that must be
  deliberate, i.e. suppressed inline, never accidental);
* ``numpy-on-traced`` / ``traced-coercion`` — ``np.*`` or
  ``int()/float()/bool()`` applied directly to a traced parameter of a
  jit root (parameters named in ``static_argnames`` are concrete and
  exempt).  Checked only where parameter tracedness is *known* (the
  root itself) — precision over recall, so every finding is actionable.
"""

from __future__ import annotations

import ast

from kubernetesclustercapacity_tpu.analysis.callgraph import CallGraph, dotted
from kubernetesclustercapacity_tpu.analysis.engine import Finding, Project

__all__ = ["check", "RULE", "IMPURE_SUBSYSTEMS"]

RULE = "jit-purity"

#: Package-relative module heads that are host-side by construction: an
#: edge from traced code into any of these is a finding regardless of
#: what the callee does today.
IMPURE_SUBSYSTEMS = frozenset(
    {
        "telemetry",
        "devcache",
        "audit",
        "timeline",
        "service",
        "resilience",
        "testing_faults",
        "follower",
        "kubeapi",
        "sources",
        "native",
        "report",
        "cli",
        "analysis",
    }
)

_IMPURE_BUILTINS = frozenset({"print", "input", "open", "breakpoint"})
_COERCIONS = frozenset({"int", "float", "bool"})

_HOST_CALLBACKS = (
    "jax.pure_callback",
    "jax.experimental.io_callback",
    "jax.debug.print",
    "jax.debug.callback",
    "jax.debug.breakpoint",
    "jax.experimental.host_callback",
)


def _subsystem(graph: CallGraph, qname: str) -> str | None:
    """Package-relative first module segment of a function's module."""
    info = graph.functions.get(qname)
    if info is None:
        return None
    head = info.module.split(".")[1] if "." in info.module else info.module
    return head


def _short(qname: str) -> str:
    """Drop the package prefix for readable messages."""
    parts = qname.split(".")
    return ".".join(parts[1:]) if len(parts) > 1 else qname


def check(project: Project):
    graph = CallGraph.build(project)
    findings: list[Finding] = []

    # --- reachability with boundary pruning: an edge into a host
    # subsystem is a finding, and traversal stops there (flagging the
    # subsystem's own internals would bury the one actionable site).
    pred: dict[str, tuple[str, object]] = {}
    queue: list[str] = []
    for root in graph.roots():
        pred[root.qname] = ("", None)
        queue.append(root.qname)
    while queue:
        cur = queue.pop(0)
        cur_info = graph.functions[cur]
        for edge in graph.edges.get(cur, ()):
            sub = _subsystem(graph, edge.target)
            if sub in IMPURE_SUBSYSTEMS:
                chain = " -> ".join(_short(q) for q in graph.chain(pred, cur))
                findings.append(
                    Finding(
                        rule=RULE,
                        severity="error",
                        path=cur_info.src.rel_path,
                        line=edge.line,
                        col=edge.col,
                        message=(
                            f"host-subsystem: call into {_short(edge.target)}"
                            f" ({sub}/) is reachable from jit root via "
                            f"{chain}"
                        ),
                        symbol=f"{cur}->{edge.target}",
                    )
                )
                continue
            if edge.target not in pred:
                pred[edge.target] = (cur, edge)
                queue.append(edge.target)

    # --- per-function purity scan of everything reachable.
    for qname in sorted(pred):
        info = graph.functions[qname]
        idx = graph.modules[info.module]
        chain = " -> ".join(_short(q) for q in graph.chain(pred, qname))
        local_bound = graph._local_bindings(info.node)
        traced: frozenset = frozenset()
        if info.is_jit_root:
            traced = frozenset(
                p
                for p in graph._params(info.node.args)
                if p not in info.static_args and p != "self"
            )

        def emit(node, category: str, detail: str) -> None:
            findings.append(
                Finding(
                    rule=RULE,
                    severity="error",
                    path=info.src.rel_path,
                    line=node.lineno,
                    col=node.col_offset,
                    message=(
                        f"{category}: {detail} inside jitted region "
                        f"({chain})"
                    ),
                    symbol=f"{qname}::{category}::{detail}",
                )
            )

        for node in graph._walk_scope(info.node):
            if isinstance(node, ast.With) or isinstance(node, ast.AsyncWith):
                for item in node.items:
                    path = dotted(item.context_expr)
                    if path and "lock" in path.rsplit(".", 1)[-1].lower():
                        emit(node, "lock", f"`with {path}:` acquisition")
                continue
            if isinstance(node, ast.Attribute):
                path = dotted(node)
                canon = (
                    graph._resolve_in(idx, info, path, local_bound)
                    if path
                    else None
                )
                if canon == "os.environ":
                    # Every environ use (attribute call, subscript, or
                    # bare) contains exactly this inner attribute node,
                    # so flagging it once here covers all forms without
                    # double-reporting the enclosing call.
                    emit(node, "io", "os.environ access")
                continue
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            fn_path = dotted(fn)
            canon = (
                graph._resolve_in(idx, info, fn_path, local_bound)
                if fn_path
                else None
            )
            if isinstance(fn, ast.Attribute) and fn.attr == "acquire":
                emit(node, "lock", f"`{fn_path or '<expr>.acquire'}()` call")
                continue
            if canon is None:
                if (
                    isinstance(fn, ast.Name)
                    and fn.id in _IMPURE_BUILTINS
                    and fn.id not in local_bound
                ):
                    emit(node, "io", f"`{fn.id}()` call")
                elif (
                    isinstance(fn, ast.Name)
                    and fn.id in _COERCIONS
                    and fn.id not in local_bound
                    and traced
                ):
                    for arg in node.args:
                        if isinstance(arg, ast.Name) and arg.id in traced:
                            emit(
                                node,
                                "traced-coercion",
                                f"`{fn.id}({arg.id})` coerces a traced "
                                "parameter to a Python scalar",
                            )
                            break
                continue
            if canon == "time" or canon.startswith("time."):
                emit(node, "clock", f"`{canon}()` call")
            elif canon == "random" or canon.startswith("random."):
                emit(node, "random", f"`{canon}()` call")
            elif canon == "os.getenv":
                emit(node, "io", f"`{canon}()` call")
            elif canon.startswith("threading."):
                emit(node, "lock", f"`{canon}()` construction")
            elif canon.startswith(_HOST_CALLBACKS):
                emit(node, "host-callback", f"`{canon}` host callback")
            elif canon.startswith("numpy.") and traced:
                for arg in node.args:
                    if isinstance(arg, ast.Name) and arg.id in traced:
                        emit(
                            node,
                            "numpy-on-traced",
                            f"`{canon}({arg.id})` applies host numpy to "
                            "a traced parameter",
                        )
                        break
    return findings
