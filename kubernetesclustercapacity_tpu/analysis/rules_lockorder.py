"""Static lock-order prover: no two locks are acquired in both orders.

A deadlock needs no bad luck, only a cycle in the lock-order graph:
thread 1 holds A and wants B while thread 2 holds B and wants A.  The
AST can build that graph without running anything — every ``with
self._lock:`` / ``with _MODULE_LOCK:`` acquisition site is visible, and
the PR 8 call graph says which *other* acquisitions are reachable from
inside a held region.  This rule walks both:

* **lexical edges** — ``with A:`` containing ``with B:`` adds A→B at
  the inner acquisition's exact ``file:line``;
* **interprocedural edges** — a call to ``g()`` inside ``with A:``
  adds A→L for every lock L that ``g`` (transitively, over resolved
  intra-package call edges) acquires, anchored at the call site with
  the callee's own acquisition site named in the message.

Lock identity is ``Class.attr`` for ``self.X`` locks (one identity per
class — instances share the discipline) and ``module:NAME`` for
module-level locks.  Cycles are reported one finding per participating
edge, so each inversion shows up at BOTH acquisition orders' exact
sites; the baseline symbol is the edge (``A->B``), line-independent as
usual.  Self-edges (re-acquiring a held lock) are deliberately out of
scope: ``RLock`` makes them legal, and the ``*_locked`` convention
already marks the helpers that run lock-held.

The runtime sanitizer (:mod:`.sanitize`) builds the same graph from
*observed* acquisitions; this rule is the static half of that pair —
it sees orders no test schedule happened to execute.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from kubernetesclustercapacity_tpu.analysis.callgraph import CallGraph
from kubernetesclustercapacity_tpu.analysis.engine import Finding, Project
from kubernetesclustercapacity_tpu.analysis.rules_locks import (
    _is_lock_ctor,
    _module_lock_aliases,
    _self_attr,
    lock_model,
)

__all__ = ["check", "build_order_graph", "RULE"]

RULE = "lock-order"


@dataclass
class _Site:
    path: str
    line: int
    col: int
    note: str = ""


@dataclass
class _OrderGraph:
    """Edges ``held -> acquired`` with first-seen acquisition sites."""

    edges: dict = field(default_factory=dict)  # (a, b) -> _Site

    def add(self, a: str, b: str, site: _Site) -> None:
        if a != b and (a, b) not in self.edges:
            self.edges[(a, b)] = site

    def successors(self) -> dict:
        out: dict[str, set[str]] = {}
        for a, b in self.edges:
            out.setdefault(a, set()).add(b)
            out.setdefault(b, set())
        return out

    def cycle_edges(self) -> list:
        """Edges that sit on a cycle: (a, b) where b reaches a."""
        succ = self.successors()
        reach_cache: dict[str, set[str]] = {}

        def reach(start: str) -> set[str]:
            hit = reach_cache.get(start)
            if hit is not None:
                return hit
            seen: set[str] = set()
            stack = [start]
            while stack:
                cur = stack.pop()
                for nxt in succ.get(cur, ()):
                    if nxt not in seen:
                        seen.add(nxt)
                        stack.append(nxt)
            reach_cache[start] = seen
            return seen

        return sorted(
            (a, b) for (a, b) in self.edges if a in reach(b)
        )


def _module_locks(tree: ast.Module, lock_aliases: set[str]) -> set[str]:
    """Module-level ``NAME = threading.Lock()`` bindings."""
    out: set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Call
        ) and _is_lock_ctor(node.value, lock_aliases):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out.add(tgt.id)
    return out


class _FnVisitor:
    """One function body: lexical acquisitions, nested edges, and call
    sites made while holding locks."""

    def __init__(self, lock_ids, module_locks: set[str], module: str) -> None:
        self._lock_ids = lock_ids  # self attr -> lock id (enclosing class)
        self._module_locks = module_locks
        self._module = module
        self.acquired: dict[str, _Site] = {}  # lock id -> first site
        self.nested: list[tuple[str, str, _Site]] = []
        self.held_calls: list[tuple[ast.Call, tuple[str, ...]]] = []

    def _lock_of(self, expr) -> str | None:
        attr = _self_attr(expr)
        if attr is not None:
            return self._lock_ids.get(attr)
        if isinstance(expr, ast.Name) and expr.id in self._module_locks:
            return f"{self._module}:{expr.id}"
        return None

    def visit_body(self, stmts, held: tuple[str, ...], path: str) -> None:
        for stmt in stmts:
            self._visit(stmt, held, path)

    def _visit(self, node, held: tuple[str, ...], path: str) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            # Closures run later — whatever is held now is not then.
            body = node.body if isinstance(node.body, list) else [node.body]
            for child in body:
                self._visit(child, (), path)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            taken: list[str] = []
            for item in node.items:
                self._visit(item.context_expr, held, path)
                lock = self._lock_of(item.context_expr)
                if lock is not None and lock not in held:
                    site = _Site(path, node.lineno, node.col_offset)
                    self.acquired.setdefault(lock, site)
                    for h in held:
                        self.nested.append((h, lock, site))
                    taken.append(lock)
            inner = held + tuple(taken)
            for child in node.body:
                self._visit(child, inner, path)
            return
        if isinstance(node, ast.Call) and held:
            self.held_calls.append((node, held))
        for child in ast.iter_child_nodes(node):
            self._visit(child, held, path)


def build_order_graph(project: Project, graph: CallGraph | None = None):
    """The package's static lock-order graph plus per-function data.

    Returns ``(order_graph, acquired_by_fn)`` where ``acquired_by_fn``
    maps function qname -> {lock id: acquisition site} including
    everything reachable through resolved call edges.
    """
    if graph is None:
        graph = CallGraph.build(project)
    model = lock_model(project)

    # Per-class lock-id maps and per-module lock names.
    class_locks: dict[tuple[str, str], dict[str, str]] = {}
    for (path, cls_name), m in model.items():
        class_locks[(path, cls_name)] = {
            attr: f"{cls_name}.{attr}" for attr in m.lock_attrs
        }
    module_locks: dict[str, set[str]] = {}
    for mod_name, idx in graph.modules.items():
        module_locks[mod_name] = _module_locks(
            idx.src.tree, _module_lock_aliases(idx.src.tree)
        )

    order = _OrderGraph()
    lexical: dict[str, dict[str, _Site]] = {}
    visitors: dict[str, _FnVisitor] = {}
    for qname, info in graph.functions.items():
        lock_ids = (
            class_locks.get((info.src.rel_path, info.cls), {})
            if info.cls is not None
            else {}
        )
        v = _FnVisitor(
            lock_ids, module_locks.get(info.module, set()), info.module
        )
        held: tuple[str, ...] = ()
        if info.name.endswith("_locked") and info.cls is not None:
            # Convention: body runs with the class lock already held.
            m = model.get((info.src.rel_path, info.cls))
            if m is not None and m.lock_attrs:
                held = (f"{info.cls}.{sorted(m.lock_attrs)[0]}",)
        v.visit_body(info.node.body, held, info.src.rel_path)
        visitors[qname] = v
        lexical[qname] = dict(v.acquired)
        for a, b, site in v.nested:
            order.add(a, b, site)

    # Transitive closure: locks acquired anywhere in/under each fn.
    closure: dict[str, dict[str, _Site]] = {}

    def close(qname: str, stack: frozenset) -> dict[str, _Site]:
        hit = closure.get(qname)
        if hit is not None:
            return hit
        if qname in stack:
            return lexical.get(qname, {})
        acc = dict(lexical.get(qname, {}))
        for edge in graph.edges.get(qname, ()):
            for lock, site in close(edge.target, stack | {qname}).items():
                acc.setdefault(lock, site)
        closure[qname] = acc
        return acc

    for qname in graph.functions:
        close(qname, frozenset())

    # Interprocedural edges: a call made while holding H reaches every
    # lock in the callee's closure.
    for qname, v in visitors.items():
        info = graph.functions[qname]
        idx = graph.modules[info.module]
        local_bound = graph._local_bindings(info.node)
        for call, held in v.held_calls:
            canon = graph._call_canon(idx, info, call, local_bound)
            if canon is None:
                continue
            target = canon if canon in graph.functions else (
                graph._class_inits.get(canon)
            )
            if target is None:
                continue
            for lock, inner_site in closure.get(target, {}).items():
                for h in held:
                    order.add(
                        h,
                        lock,
                        _Site(
                            info.src.rel_path,
                            call.lineno,
                            call.col_offset,
                            note=(
                                f"via `{target.split('.', 1)[-1]}`, which "
                                f"acquires `{lock}` at "
                                f"{inner_site.path}:{inner_site.line}"
                            ),
                        ),
                    )
    return order, closure


def _cycle_string(a: str, b: str, cyc_edges: set) -> str:
    """A readable ``a -> b -> ... -> a`` walk for the message."""
    succ: dict[str, set[str]] = {}
    for x, y in cyc_edges:
        succ.setdefault(x, set()).add(y)
    path = [a, b]
    seen = {a, b}
    cur = b
    while cur != a:
        nxts = sorted(n for n in succ.get(cur, ()) if n == a or n not in seen)
        if not nxts:
            break
        cur = nxts[0]
        path.append(cur)
        seen.add(cur)
    if path[-1] != a:
        path.append(a)
    return " -> ".join(path)


def check(project: Project):
    order, _ = build_order_graph(project)
    cyc = order.cycle_edges()
    cyc_set = set(cyc)
    findings: list[Finding] = []
    for a, b in cyc:
        site = order.edges[(a, b)]
        opposing = None
        for x, y in cyc:
            if x == b or y == a:
                opposing = order.edges[(x, y)]
                if (x, y) != (a, b):
                    break
        msg = (
            f"lock-order inversion: `{b}` is acquired while holding "
            f"`{a}`, closing the cycle {_cycle_string(a, b, cyc_set)}"
        )
        if site.note:
            msg += f" ({site.note})"
        if opposing is not None and opposing is not site:
            msg += (
                f"; the opposing order is taken at "
                f"{opposing.path}:{opposing.line}"
            )
        findings.append(
            Finding(
                rule=RULE,
                severity="error",
                path=site.path,
                line=site.line,
                col=site.col,
                message=msg,
                symbol=f"{a}->{b}",
            )
        )
    return findings
