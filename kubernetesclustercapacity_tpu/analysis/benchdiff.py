"""``kccap -bench-diff``: the typed comparator over bench artifacts.

The repo carries its performance history as committed artifacts
(``BENCH_r01.json`` … ``BENCH_r05.json``, plus selfcheck runs), but
until now "did round N regress round N-1?" was a human eyeball over two
JSON blobs.  This module makes the comparison a typed, gated program:

* **artifact shapes are classified, not assumed** — a bench wrapper
  (``{n, cmd, rc, tail, parsed}``) whose ``parsed`` is ``None`` (no
  JSON tail survived) or an error dict (``{"error": ..., "value":
  null}``) is a DEGRADED round: it is *named* in the report but can
  never fail the gate, because "the harness fell over" is not "the
  code got slower".  A bare flat dict (the selfcheck artifacts) is
  rows directly.
* **per-row noise thresholds live in a committed file**
  (:data:`THRESHOLDS_FILENAME`) — each row carries ``direction``
  (``lower_is_better`` / ``higher_is_better`` / ``informational``),
  ``rel_tol`` and ``abs_tol``; unknown rows fall back to the
  ``default`` entry with direction inferred from the row name
  (``*_ms`` is latency, ``*per_sec``/``*_rps`` is throughput,
  anything else is informational).  A regression must clear BOTH
  tolerances — relative noise on a microsecond row and absolute
  noise on a milliseconds row both stay quiet.
* **gated rows respect their parity fields** — ``serving_p50_ms`` is
  only a valid number when ``serving_parity_diffs == 0`` on both
  sides (a fast wrong answer is not a fast answer); a row whose gate
  is nonzero or missing on either side is reported ``gated``, never
  compared.
* **missing and renamed rows are named, not ignored** — a row present
  in OLD but absent from NEW is exactly how a silently-dropped
  benchmark hides; it lands in ``missing`` (and new rows in
  ``added``) so the report says so, without failing the gate.
* **trajectory mode** walks every ``BENCH_r*.json`` in a directory in
  round order and diffs each consecutive comparable pair — the whole
  history audited in one command.

Exit codes mirror ``kccap-lint``: 0 clean, 1 at least one
threshold-breaching regression, 2 usage error.  ``--json`` emits the
full machine-readable artifact instead of the text report.
"""

from __future__ import annotations

import glob
import json
import math
import os
import re
from dataclasses import dataclass, field

__all__ = [
    "THRESHOLDS_FILENAME",
    "Thresholds",
    "RowDiff",
    "BenchDiff",
    "load_rows",
    "load_thresholds",
    "diff_files",
    "trajectory",
    "render",
    "render_trajectory",
]

#: The committed per-row noise-threshold file (repo root, next to the
#: BENCH_r*.json artifacts it governs).
THRESHOLDS_FILENAME = "BENCH_THRESHOLDS.json"

_DIRECTIONS = ("lower_is_better", "higher_is_better", "informational")

#: Direction inference for rows the thresholds file does not name:
#: latency-shaped names regress upward, throughput-shaped names regress
#: downward, anything else is informational (counts, config echoes).
_LOWER_PAT = re.compile(r"(_ms|_s|_seconds|_bytes)$")
_HIGHER_PAT = re.compile(r"(per_sec|_rps|_throughput)$")


def infer_direction(name: str) -> str:
    if _HIGHER_PAT.search(name):
        return "higher_is_better"
    if _LOWER_PAT.search(name):
        return "lower_is_better"
    return "informational"


class Thresholds:
    """The committed noise model: ``default`` entry + per-row
    overrides, each ``{direction?, rel_tol?, abs_tol?, gate?}``."""

    def __init__(self, spec: dict | None = None) -> None:
        spec = spec or {}
        self.default = {
            "direction": "auto",
            "rel_tol": 0.25,
            "abs_tol": 0.05,
        }
        self.default.update(spec.get("default", {}))
        self.rows: dict[str, dict] = {
            str(k): dict(v) for k, v in spec.get("rows", {}).items()
        }
        for name, row in self.rows.items():
            d = row.get("direction")
            if d is not None and d not in _DIRECTIONS:
                raise ValueError(
                    f"row {name!r}: unknown direction {d!r} "
                    f"(one of {_DIRECTIONS})"
                )

    def for_row(self, name: str) -> dict:
        """The effective ``{direction, rel_tol, abs_tol, gate}`` for a
        row — override merged over default, ``auto`` resolved by name."""
        eff = dict(self.default)
        eff.update(self.rows.get(name, {}))
        if eff.get("direction", "auto") == "auto":
            eff["direction"] = infer_direction(name)
        eff.setdefault("gate", None)
        return eff


def load_thresholds(path: str | None) -> Thresholds:
    """Load the committed thresholds file; a missing path means the
    built-in defaults (direction inference, 25%/0.05 tolerances)."""
    if path is None or not os.path.exists(path):
        return Thresholds()
    with open(path, encoding="utf-8") as f:
        return Thresholds(json.load(f))


def _numeric_rows(d: dict) -> dict[str, float]:
    out: dict[str, float] = {}
    for k, v in d.items():
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        if isinstance(v, float) and not math.isfinite(v):
            continue
        out[str(k)] = float(v)
    return out


def load_rows(path: str) -> tuple[dict[str, float], str | None]:
    """Classify one artifact into ``(rows, degraded_reason)``.

    A wrapper artifact contributes its ``parsed`` dict; ``parsed`` of
    ``None`` or an error dict (``error`` set, ``value`` null) makes the
    round degraded — rows empty, reason named.  A bare flat dict (the
    selfcheck shape) is rows directly.  A file that is not JSON or not
    a dict raises ``ValueError`` (usage error, exit 2).
    """
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: bench artifact is not a JSON object")
    if "parsed" in doc and ("cmd" in doc or "tail" in doc):
        parsed = doc.get("parsed")
        if parsed is None:
            return {}, "no parsed JSON tail (harness emitted nothing)"
        if not isinstance(parsed, dict):
            return {}, f"parsed tail is {type(parsed).__name__}, not a dict"
        if parsed.get("error") is not None and parsed.get("value") is None:
            return {}, f"degraded run: {parsed['error']}"
        return _numeric_rows(parsed), None
    return _numeric_rows(doc), None


@dataclass
class RowDiff:
    """One row's comparison: the typed unit the gate sums over."""

    name: str
    old: float
    new: float
    direction: str
    rel_tol: float
    abs_tol: float
    gate: str | None
    #: ok | regression | improved | informational | gated
    verdict: str
    note: str = ""

    @property
    def delta(self) -> float:
        return self.new - self.old

    @property
    def rel_change(self) -> float:
        if self.old == 0.0:
            return math.inf if self.new != self.old else 0.0
        return (self.new - self.old) / abs(self.old)

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "old": self.old,
            "new": self.new,
            "delta": round(self.delta, 6),
            "rel_change": (
                None
                if math.isinf(self.rel_change)
                else round(self.rel_change, 6)
            ),
            "direction": self.direction,
            "rel_tol": self.rel_tol,
            "abs_tol": self.abs_tol,
            "gate": self.gate,
            "verdict": self.verdict,
            "note": self.note,
        }


@dataclass
class BenchDiff:
    """The full comparison of two artifacts."""

    old_path: str
    new_path: str
    old_degraded: str | None
    new_degraded: str | None
    rows: list[RowDiff] = field(default_factory=list)
    missing: list[str] = field(default_factory=list)
    added: list[str] = field(default_factory=list)

    @property
    def regressions(self) -> list[RowDiff]:
        return [r for r in self.rows if r.verdict == "regression"]

    @property
    def comparable(self) -> bool:
        return self.old_degraded is None and self.new_degraded is None

    def to_json(self) -> dict:
        return {
            "old": self.old_path,
            "new": self.new_path,
            "old_degraded": self.old_degraded,
            "new_degraded": self.new_degraded,
            "comparable": self.comparable,
            "rows": [r.to_json() for r in self.rows],
            "missing": list(self.missing),
            "added": list(self.added),
            "regressions": [r.name for r in self.regressions],
        }


def diff_rows(
    old: dict[str, float],
    new: dict[str, float],
    thresholds: Thresholds,
) -> tuple[list[RowDiff], list[str], list[str]]:
    """Compare two row dicts under the noise model; returns
    ``(row_diffs, missing_in_new, added_in_new)``."""
    out: list[RowDiff] = []
    for name in sorted(old):
        if name not in new:
            continue
        eff = thresholds.for_row(name)
        rd = RowDiff(
            name=name,
            old=old[name],
            new=new[name],
            direction=eff["direction"],
            rel_tol=float(eff["rel_tol"]),
            abs_tol=float(eff["abs_tol"]),
            gate=eff["gate"],
            verdict="ok",
        )
        gate = eff["gate"]
        if gate is not None:
            og, ng = old.get(gate), new.get(gate)
            if og is None or ng is None:
                rd.verdict = "gated"
                rd.note = f"gate row {gate!r} missing"
                out.append(rd)
                continue
            if og != 0 or ng != 0:
                rd.verdict = "gated"
                rd.note = (
                    f"gate {gate}={og:g}->{ng:g} nonzero — row not a "
                    "valid measurement"
                )
                out.append(rd)
                continue
        if rd.direction == "informational":
            rd.verdict = "informational"
            out.append(rd)
            continue
        worse = (
            rd.delta if rd.direction == "lower_is_better" else -rd.delta
        )
        rel = abs(rd.rel_change) if rd.old != 0.0 else math.inf
        if worse > 0 and rel > rd.rel_tol and abs(worse) > rd.abs_tol:
            rd.verdict = "regression"
            rd.note = (
                f"{rel * 100:.1f}% worse (tol {rd.rel_tol * 100:.0f}%, "
                f"abs {rd.abs_tol:g})"
            )
        elif worse < 0 and rel > rd.rel_tol and abs(worse) > rd.abs_tol:
            rd.verdict = "improved"
        out.append(rd)
    missing = sorted(k for k in old if k not in new)
    added = sorted(k for k in new if k not in old)
    return out, missing, added


def diff_files(
    old_path: str, new_path: str, thresholds: Thresholds
) -> BenchDiff:
    """Compare two artifacts on disk (the ``kccap -bench-diff OLD NEW``
    core).  Degraded artifacts produce a named, empty, never-failing
    comparison."""
    old_rows, old_deg = load_rows(old_path)
    new_rows, new_deg = load_rows(new_path)
    bd = BenchDiff(
        old_path=old_path,
        new_path=new_path,
        old_degraded=old_deg,
        new_degraded=new_deg,
    )
    if not bd.comparable:
        return bd
    bd.rows, bd.missing, bd.added = diff_rows(
        old_rows, new_rows, thresholds
    )
    return bd


_ROUND_PAT = re.compile(r"BENCH_r(\d+)\.json$")


def trajectory(
    directory: str, thresholds: Thresholds
) -> list[BenchDiff]:
    """Walk every ``BENCH_r*.json`` in ``directory`` in round order and
    diff each consecutive pair (degraded rounds stay in the walk — the
    pair is emitted, named degraded, and skipped by the gate)."""
    paths = []
    for p in glob.glob(os.path.join(directory, "BENCH_r*.json")):
        m = _ROUND_PAT.search(os.path.basename(p))
        if m:
            paths.append((int(m.group(1)), p))
    paths.sort()
    if len(paths) < 2:
        raise ValueError(
            f"{directory}: trajectory mode needs >= 2 BENCH_r*.json "
            f"rounds (found {len(paths)})"
        )
    return [
        diff_files(paths[i][1], paths[i + 1][1], thresholds)
        for i in range(len(paths) - 1)
    ]


# -- text rendering ---------------------------------------------------


def _fmt(v: float) -> str:
    return f"{v:g}"


def render(bd: BenchDiff) -> str:
    """The human report for one pair (regressions first, then the
    bookkeeping nobody may silently drop)."""
    lines = [f"bench-diff: {bd.old_path} -> {bd.new_path}"]
    if bd.old_degraded:
        lines.append(f"  OLD degraded: {bd.old_degraded}")
    if bd.new_degraded:
        lines.append(f"  NEW degraded: {bd.new_degraded}")
    if not bd.comparable:
        lines.append(
            "  not comparable — degraded rounds are named, never "
            "failed"
        )
        return "\n".join(lines)
    for r in bd.regressions:
        lines.append(
            f"  REGRESSION {r.name}: {_fmt(r.old)} -> {_fmt(r.new)} "
            f"({r.note})"
        )
    for r in bd.rows:
        if r.verdict == "improved":
            lines.append(
                f"  improved   {r.name}: {_fmt(r.old)} -> {_fmt(r.new)}"
            )
        elif r.verdict == "gated":
            lines.append(f"  gated      {r.name}: {r.note}")
    for name in bd.missing:
        lines.append(f"  missing    {name}: in OLD, absent from NEW")
    for name in bd.added:
        lines.append(f"  added      {name}: new in NEW")
    n_ok = sum(1 for r in bd.rows if r.verdict in ("ok", "informational"))
    lines.append(
        f"  {len(bd.regressions)} regression(s), "
        f"{sum(1 for r in bd.rows if r.verdict == 'improved')} "
        f"improved, {n_ok} within noise, "
        f"{sum(1 for r in bd.rows if r.verdict == 'gated')} gated, "
        f"{len(bd.missing)} missing, {len(bd.added)} added"
    )
    return "\n".join(lines)


def render_trajectory(diffs: list[BenchDiff]) -> str:
    out = [render(bd) for bd in diffs]
    total = sum(len(bd.regressions) for bd in diffs)
    out.append(
        f"trajectory: {len(diffs)} pair(s) walked, {total} "
        "regression(s) total"
    )
    return "\n\n".join(out)
