"""Intra-package call graph + jit-root discovery (AST only, no imports).

The graph is deliberately *conservative in reachability* and *precise in
resolution*: an edge exists only when a call or bare function reference
resolves through the module's real import/def bindings (so ``time.time``
shadowed by a local never edges to stdlib ``time``), but every resolved
reference counts — including functions passed as values (``jax.vmap(f)``,
``pl.pallas_call(make_kernel(...))``) — because inside a traced region a
referenced function is as good as a called one.

Jit roots are found three ways:

* decorators — ``@jax.jit``, ``@partial(jax.jit, static_argnames=...)``,
  ``@pjit``, with ``static_argnames`` captured so purity rules know which
  parameters hold *concrete* (non-traced) values;
* wrap calls — any ``jax.jit(...)`` / ``pjit(...)`` / ``pallas_call(...)``
  call anywhere (module level included): every function referenced in its
  arguments becomes a root (this catches ``jax.jit(checkify.checkify(f))``
  and ``pl.pallas_call(make_kernel(...), ...)``);
* nested defs of a root are reachable unconditionally (a def statement
  executes at trace time, and closures like pallas kernel factories are
  exactly the case that matters).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from kubernetesclustercapacity_tpu.analysis.engine import Project, SourceFile

__all__ = ["CallGraph", "FunctionInfo", "Edge", "dotted"]

#: Canonical dotted names that mean "this wraps its argument in jit".
_JIT_WRAPPERS = {
    "jax.jit",
    "jax.pjit",
    "jax.experimental.pjit.pjit",
}
_JIT_WRAPPER_SUFFIXES = (".pallas_call",)

_PARTIAL_NAMES = {"functools.partial", "partial"}


def dotted(node: ast.AST) -> str | None:
    """``a.b.c`` attribute chain -> ``"a.b.c"``, else ``None``."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass
class Edge:
    """A resolved intra-package reference from one function to another."""

    target: str  # canonical qname
    line: int
    col: int
    kind: str  # "call" | "ref" | "nested"


@dataclass
class FunctionInfo:
    qname: str  # canonical dotted: module path + [Class.]name chain
    module: str
    src: SourceFile
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    cls: str | None = None
    static_args: frozenset = frozenset()
    jit_reasons: list[str] = field(default_factory=list)

    @property
    def is_jit_root(self) -> bool:
        return bool(self.jit_reasons)

    @property
    def name(self) -> str:
        return self.qname.rsplit(".", 1)[-1]


class _ModuleIndex:
    """Per-module bindings: imports, top-level defs, classes."""

    def __init__(self, name: str, src: SourceFile, is_pkg: bool) -> None:
        self.name = name
        self.src = src
        # The package relative imports resolve against.
        self.package = name if is_pkg else name.rsplit(".", 1)[0]
        self.imports: dict[str, str] = {}  # local alias -> dotted target
        self.toplevel: dict[str, str] = {}  # local name -> canonical qname
        self.class_methods: dict[str, dict[str, str]] = {}
        self.class_bases: dict[str, list[str]] = {}

    def add_imports(self, tree: ast.Module) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        self.imports[alias.asname] = alias.name
                    else:
                        top = alias.name.split(".", 1)[0]
                        self.imports[top] = top
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    up = self.package.split(".")
                    # level 1 = current package; each extra level pops one.
                    up = up[: len(up) - (node.level - 1)]
                    base = ".".join(up + ([node.module] if node.module else []))
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    local = alias.asname or alias.name
                    self.imports[local] = f"{base}.{alias.name}"

    def resolve(self, name_path: str) -> str | None:
        """Local dotted reference -> canonical dotted name (or None)."""
        head, _, rest = name_path.partition(".")
        if head in self.toplevel:
            base = self.toplevel[head]
        elif head in self.imports:
            base = self.imports[head]
        else:
            return None
        return f"{base}.{rest}" if rest else base


class CallGraph:
    def __init__(self, project: Project) -> None:
        self.project = project
        self.functions: dict[str, FunctionInfo] = {}
        self.edges: dict[str, list[Edge]] = {}
        self.modules: dict[str, _ModuleIndex] = {}
        self._class_inits: dict[str, str] = {}  # class qname -> __init__ qname

    # ------------------------------------------------------------------
    @classmethod
    def build(cls, project: Project) -> "CallGraph":
        g = cls(project)
        indexed: list[tuple[_ModuleIndex, SourceFile]] = []
        for src in project.files:
            mod_name = g._module_name(src)
            idx = _ModuleIndex(
                mod_name, src, is_pkg=src.rel_path.endswith("__init__.py")
            )
            idx.add_imports(src.tree)
            g.modules[mod_name] = idx
            g._collect_defs(idx, src.tree, prefix=mod_name, cls=None)
            indexed.append((idx, src))
        # Second pass: edges + jit roots need every module's defs known.
        for idx, src in indexed:
            g._scan_module(idx, src)
        return g

    def _module_name(self, src: SourceFile) -> str:
        rel = src.rel_path
        # rel is repo-root relative; strip down to package-relative.
        pkg = self.project.package_name
        parts = rel[: -len(".py")].split("/")
        if pkg in parts:
            parts = parts[parts.index(pkg) :]
        if parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(parts)

    # ------------------------------------------------------------------
    @staticmethod
    def _own_defs(scope_node):
        """Function/class defs belonging directly to this scope — defs
        under if/try/with/loops included, defs inside nested defs or
        classes excluded (those are their own scopes)."""
        compound = (
            ast.If, ast.For, ast.While, ast.With, ast.Try,
            ast.AsyncFor, ast.AsyncWith,
        )
        stack = [scope_node]
        while stack:
            item = stack.pop()
            for child in ast.iter_child_nodes(item):
                if isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
                ):
                    yield child
                elif isinstance(child, compound):
                    stack.append(child)

    def _collect_defs(self, idx, scope_node, prefix: str, cls) -> None:
        for node in self._own_defs(scope_node):
            qname = f"{prefix}.{node.name}"
            if isinstance(node, ast.ClassDef):
                if prefix == idx.name:
                    idx.toplevel[node.name] = qname
                    idx.class_bases[node.name] = [
                        d for d in (dotted(b) for b in node.bases) if d
                    ]
                self._collect_defs(
                    idx, node, qname,
                    cls=node.name if prefix == idx.name else cls,
                )
                continue
            if qname in self.functions:
                # Same-named sibling (e.g. two `def _():` under pl.when):
                # uniquify so both bodies stay analyzable.
                qname = f"{qname}@{node.lineno}"
            info = FunctionInfo(
                qname=qname, module=idx.name, src=idx.src, node=node, cls=cls
            )
            self.functions[qname] = info
            if cls is None and prefix == idx.name:
                idx.toplevel[node.name] = qname
            if cls is not None and prefix == f"{idx.name}.{cls}":
                idx.class_methods.setdefault(cls, {})[node.name] = qname
                if node.name == "__init__":
                    self._class_inits[f"{idx.name}.{cls}"] = qname
            self._collect_defs(idx, node, qname, cls)

    # ------------------------------------------------------------------
    def _scan_module(self, idx: _ModuleIndex, src: SourceFile) -> None:
        # Module-level statements: jit-wrap detection only (module bodies
        # execute at import, outside any traced region).
        self._find_jit_wraps(idx, src.tree, scope_prefix=idx.name, cls=None)
        for qname, info in list(self.functions.items()):
            if info.module != idx.name:
                continue
            self._scan_function(idx, info)

    @staticmethod
    def _params(args: ast.arguments) -> list[str]:
        out = [
            a.arg
            for a in (
                list(args.posonlyargs)
                + list(args.args)
                + list(args.kwonlyargs)
            )
        ]
        if args.vararg:
            out.append(args.vararg.arg)
        if args.kwarg:
            out.append(args.kwarg.arg)
        return out

    def _local_bindings(self, node) -> set[str]:
        """Names bound inside this function's scope (params, assignments,
        imports, nested def/class names, lambda params) — used to keep
        shadowed imports/globals from resolving."""
        bound: set[str] = set()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            bound.update(self._params(node.args))
        for sub in self._walk_scope(node):
            if isinstance(sub, ast.Name) and isinstance(
                sub.ctx, (ast.Store, ast.Del)
            ):
                bound.add(sub.id)
            elif isinstance(sub, (ast.Import, ast.ImportFrom)):
                for alias in sub.names:
                    bound.add((alias.asname or alias.name).split(".", 1)[0])
            elif isinstance(
                sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                bound.add(sub.name)
            elif isinstance(sub, ast.Lambda):
                # Lambda bodies are scanned inline (vmap callbacks);
                # their params must still shadow.
                bound.update(self._params(sub.args))
        return bound

    def _walk_scope(self, node):
        """Every AST node in ``node``'s own runtime scope.

        Annotations are skipped (never executed under ``from __future__
        import annotations``); a nested def is yielded *shallowly* (its
        def statement — name binding, decorators, defaults — executes
        here) but its body is a separate scope.  Lambda bodies stay
        inline: in this codebase they are vmap/callback bodies whose
        expressions trace with the enclosing function.
        """
        stack = [(node, True, True)]  # (node, expand, is_top)
        while stack:
            item, expand, is_top = stack.pop()
            if not is_top:
                yield item
            if not expand:
                # Shallow nested def: decorators + defaults run here.
                for dec in item.decorator_list:
                    stack.append((dec, True, False))
                for d in item.args.defaults:
                    stack.append((d, True, False))
                for kd in item.args.kw_defaults:
                    if kd is not None:
                        stack.append((kd, True, False))
                continue
            for name, value in ast.iter_fields(item):
                if name in ("annotation", "returns"):
                    continue
                if is_top and name == "decorator_list":
                    # The top node's own decorators execute in the
                    # ENCLOSING scope, not this one.
                    continue
                for child in value if isinstance(value, list) else [value]:
                    if not isinstance(child, ast.AST):
                        continue
                    if isinstance(
                        child, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        stack.append((child, False, False))
                    else:
                        stack.append((child, True, False))

    # ------------------------------------------------------------------
    def _resolve_in(self, idx, info: FunctionInfo | None, name_path: str,
                    local_bound: set[str]):
        """Resolve a dotted reference in a function/module scope to a
        canonical dotted name, or None."""
        head = name_path.split(".", 1)[0]
        if head in ("self", "cls") and info is not None and info.cls is not None:
            rest = name_path.split(".", 1)[1] if "." in name_path else ""
            if rest and "." not in rest:
                return self._resolve_method(idx, info.cls, rest)
            return None
        if head in local_bound:
            # Shadowed by a parameter/local — except locally nested defs,
            # which resolve to their canonical nested qname.
            if info is not None:
                nested = f"{info.qname}.{head}"
                if nested in self.functions and "." not in name_path:
                    return nested
            return None
        return idx.resolve(name_path)

    def _resolve_method(self, idx, cls: str, meth: str) -> str | None:
        seen: set[str] = set()
        stack = [cls]
        while stack:
            c = stack.pop()
            if c in seen:
                continue
            seen.add(c)
            hit = idx.class_methods.get(c, {}).get(meth)
            if hit:
                return hit
            for base in idx.class_bases.get(c, ()):
                if "." not in base:
                    stack.append(base)
        return None

    # ------------------------------------------------------------------
    def _is_jit_wrapper(self, canon: str | None) -> bool:
        if canon is None:
            return False
        return canon in _JIT_WRAPPERS or canon.endswith(_JIT_WRAPPER_SUFFIXES)

    def _find_jit_wraps(self, idx, scope_node, scope_prefix: str, cls) -> None:
        """Mark roots from ``jit(...)`` wrap calls in a scope (module
        bodies and function bodies both funnel here)."""
        info = self.functions.get(scope_prefix)
        local_bound = (
            self._local_bindings(info.node) if info is not None else set()
        )
        for node in self._walk_scope(scope_node):
            if not isinstance(node, ast.Call):
                continue
            canon = self._call_canon(idx, info, node, local_bound)
            if not self._is_jit_wrapper(canon):
                continue
            for ref in self._function_refs_in_args(idx, info, node, local_bound):
                self._mark_root(ref, f"wrapped by {canon}")

    def _call_canon(self, idx, info, call: ast.Call, local_bound):
        path = dotted(call.func)
        if path is None:
            return None
        return self._resolve_in(idx, info, path, local_bound)

    def _function_refs_in_args(self, idx, info, call: ast.Call, local_bound):
        """Every known function referenced anywhere in a call's
        arguments (descending into nested calls)."""
        out = []
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            for node in [arg, *ast.walk(arg)]:
                if isinstance(node, (ast.Name, ast.Attribute)):
                    path = dotted(node)
                    if path is None:
                        continue
                    canon = self._resolve_in(idx, info, path, local_bound)
                    if canon in self.functions:
                        out.append(canon)
        return out

    def _mark_root(self, qname: str, reason: str) -> None:
        info = self.functions.get(qname)
        if info is not None and reason not in info.jit_reasons:
            info.jit_reasons.append(reason)

    @staticmethod
    def _static_argnames_from_call(call: ast.Call) -> frozenset:
        for kw in call.keywords:
            if kw.arg in ("static_argnames", "static_argnums"):
                names = []
                val = kw.value
                elts = val.elts if isinstance(val, (ast.Tuple, ast.List)) else [val]
                for e in elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value, str):
                        names.append(e.value)
                return frozenset(names)
        return frozenset()

    def _scan_decorators(self, idx, info: FunctionInfo) -> None:
        for dec in info.node.decorator_list:
            canon = None
            static: frozenset = frozenset()
            path = dotted(dec)
            if path is not None:
                canon = idx.resolve(path)
            elif isinstance(dec, ast.Call):
                fn_canon = idx.resolve(dotted(dec.func) or "")
                if fn_canon in _PARTIAL_NAMES or fn_canon == "functools.partial":
                    if dec.args:
                        inner = dotted(dec.args[0])
                        canon = idx.resolve(inner) if inner else None
                        static = self._static_argnames_from_call(dec)
                elif self._is_jit_wrapper(fn_canon):
                    # @jax.jit(static_argnames=...) factory form.
                    canon = fn_canon
                    static = self._static_argnames_from_call(dec)
            if self._is_jit_wrapper(canon):
                info.static_args = info.static_args | static
                self._mark_root(info.qname, f"decorated with {canon}")

    # ------------------------------------------------------------------
    def _scan_function(self, idx, info: FunctionInfo) -> None:
        self._scan_decorators(idx, info)
        edges = self.edges.setdefault(info.qname, [])
        seen_sites: set[tuple[str, int, int]] = set()

        def add_edge(target: str, line: int, col: int, kind: str) -> None:
            # A call's func Name is visited both as the Call and as a
            # bare Load — one site, one edge.
            site = (target, line, col)
            if site not in seen_sites:
                seen_sites.add(site)
                edges.append(Edge(target, line, col, kind))

        local_bound = self._local_bindings(info.node)
        # Nested defs execute (their def statement) in this scope — they
        # are reachable the moment the enclosing function runs.
        for child in self._walk_scope(info.node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nested = f"{info.qname}.{child.name}"
                if nested not in self.functions:
                    nested = f"{nested}@{child.lineno}"
                if nested in self.functions:
                    add_edge(nested, child.lineno, child.col_offset, "nested")
        for node in self._walk_scope(info.node):
            if isinstance(node, ast.Call):
                canon = self._call_canon(idx, info, node, local_bound)
                if self._is_jit_wrapper(canon):
                    for ref in self._function_refs_in_args(
                        idx, info, node, local_bound
                    ):
                        self._mark_root(ref, f"wrapped by {canon}")
                    continue
                if canon is not None:
                    target = self.functions.get(canon) and canon
                    if target is None and canon in self._class_inits:
                        target = self._class_inits[canon]
                    if target is not None:
                        add_edge(target, node.lineno, node.col_offset, "call")
                        continue
            elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                canon = self._resolve_in(idx, info, node.id, local_bound)
                if canon in self.functions:
                    add_edge(canon, node.lineno, node.col_offset, "ref")

    # ------------------------------------------------------------------
    def roots(self) -> list[FunctionInfo]:
        return [f for f in self.functions.values() if f.is_jit_root]

    def reachable(self) -> dict[str, tuple[str, Edge | None]]:
        """BFS from every jit root.

        Returns ``{qname: (predecessor_qname, entering_edge)}`` for every
        function reachable from a root; roots map to ``("", None)``.
        """
        pred: dict[str, tuple[str, Edge | None]] = {}
        queue: list[str] = []
        for f in self.roots():
            pred[f.qname] = ("", None)
            queue.append(f.qname)
        while queue:
            cur = queue.pop(0)
            for edge in self.edges.get(cur, ()):  # deterministic order
                if edge.target not in pred:
                    pred[edge.target] = (cur, edge)
                    queue.append(edge.target)
        return pred

    def chain(self, pred: dict, qname: str) -> list[str]:
        """Root -> ... -> qname, for finding messages."""
        out = [qname]
        seen = {qname}
        while True:
            p, _ = pred.get(out[-1], ("", None))
            if not p or p in seen:
                break
            out.append(p)
            seen.add(p)
        return list(reversed(out))
