"""Hygiene walks that keep the tree clean without external tooling.

The satellite CI story wires ``ruff`` into ``pyproject.toml``, but the
analyzer must not *depend* on ruff existing (this environment bakes no
linter into the image).  This module carries the highest-value pyflakes
subset natively so the tier-1 gate enforces it everywhere:

* ``hygiene-unused-import`` — an imported name never referenced in the
  module.  ``__init__.py`` files are exempt (the re-export idiom), as
  are ``__future__`` imports and names listed in ``__all__``.
"""

from __future__ import annotations

import ast

from kubernetesclustercapacity_tpu.analysis.engine import Finding, Project

__all__ = ["check"]


def _used_names(tree: ast.Module) -> set[str]:
    used: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            # `a.b` usage of `import a.b` style bindings is covered by
            # the base Name; nothing extra needed here.
            pass
    return used


def _exported_names(tree: ast.Module) -> set[str]:
    out: set[str] = set()
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == "__all__"
                for t in node.targets
            )
            and isinstance(node.value, (ast.List, ast.Tuple))
        ):
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(
                    elt.value, str
                ):
                    out.add(elt.value)
    return out


def check(project: Project):
    findings: list[Finding] = []
    for src in project.files:
        if src.rel_path.endswith("__init__.py"):
            continue
        used = _used_names(src.tree)
        exported = _exported_names(src.tree)
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Import):
                bindings = [
                    (
                        alias.asname
                        if alias.asname
                        else alias.name.split(".", 1)[0],
                        alias.name,
                    )
                    for alias in node.names
                ]
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                bindings = [
                    (alias.asname or alias.name, alias.name)
                    for alias in node.names
                    if alias.name != "*"
                ]
            else:
                continue
            for local, original in bindings:
                if local in used or local in exported:
                    continue
                findings.append(
                    Finding(
                        rule="hygiene-unused-import",
                        severity="warning",
                        path=src.rel_path,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"`{original}` is imported as `{local}` but "
                            "never used in this module"
                        ),
                        symbol=f"{local}",
                    )
                )
    return findings
