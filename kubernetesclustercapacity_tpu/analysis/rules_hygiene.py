"""Hygiene walks that keep the tree clean without external tooling.

The satellite CI story wires ``ruff`` into ``pyproject.toml``, but the
analyzer must not *depend* on ruff existing (this environment bakes no
linter into the image).  This module carries the highest-value pyflakes
subset natively so the tier-1 gate enforces it everywhere:

* ``hygiene-unused-import`` — an imported name never referenced in the
  module.  ``__init__.py`` files are exempt (the re-export idiom), as
  are ``__future__`` imports and names listed in ``__all__``.
* ``hygiene-thread-death`` — a ``threading.Thread`` target whose body
  can raise outside any ``try``/``except``.  A worker that dies
  silently is how lockset gaps hide: the thread's absence looks like
  quiescence, its unjoined exception goes to a stderr hook nobody
  reads, and every invariant it maintained (heartbeats, queue drains,
  breaker resets) silently stops holding.  A target is *protected*
  when every statement that can raise sits inside a ``try`` with a
  handler (docstrings, constant assignments, ``return``/``pass`` are
  raise-free; loops and ``if``/``with`` bodies are checked
  recursively).  Deliberately-fragile workers suppress at the
  ``Thread(...)`` site with the usual ``lint-ok`` marker and a reason.
"""

from __future__ import annotations

import ast

from kubernetesclustercapacity_tpu.analysis.callgraph import dotted
from kubernetesclustercapacity_tpu.analysis.engine import Finding, Project

__all__ = ["check"]


def _used_names(tree: ast.Module) -> set[str]:
    used: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            # `a.b` usage of `import a.b` style bindings is covered by
            # the base Name; nothing extra needed here.
            pass
    return used


def _exported_names(tree: ast.Module) -> set[str]:
    out: set[str] = set()
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == "__all__"
                for t in node.targets
            )
            and isinstance(node.value, (ast.List, ast.Tuple))
        ):
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(
                    elt.value, str
                ):
                    out.add(elt.value)
    return out


def _is_trivial_expr(node) -> bool:
    """Expressions that cannot raise: constants, bare names, and
    attribute chains off them (``self.x`` can raise AttributeError in
    principle; in a worker body that is a programming error the rule
    should surface, so only Name/Constant are trivial)."""
    return isinstance(node, (ast.Constant, ast.Name))


def _protected_stmt(stmt) -> bool:
    """Can this statement raise outside a try/except?"""
    if isinstance(stmt, ast.Try):
        # A try with no handler (try/finally) protects nothing.
        return bool(stmt.handlers) and _protected_body(
            stmt.orelse
        ) and _protected_body(stmt.finalbody)
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
        return True  # docstring
    if isinstance(stmt, (ast.Pass, ast.Break, ast.Continue)):
        return True
    if isinstance(stmt, ast.Return):
        return stmt.value is None or _is_trivial_expr(stmt.value)
    if isinstance(stmt, ast.Assign):
        return _is_trivial_expr(stmt.value) and all(
            isinstance(t, ast.Name) for t in stmt.targets
        )
    if isinstance(stmt, ast.While):
        return _is_trivial_expr(stmt.test) and _protected_body(
            stmt.body
        ) and _protected_body(stmt.orelse)
    if isinstance(stmt, ast.If):
        return (
            _is_trivial_expr(stmt.test)
            and _protected_body(stmt.body)
            and _protected_body(stmt.orelse)
        )
    return False


def _protected_body(stmts) -> bool:
    return all(_protected_stmt(s) for s in stmts)


def _thread_targets(src) -> list:
    """``threading.Thread(target=X)`` sites -> (call node, target name,
    enclosing class name or None)."""
    out = []
    class_of: dict[int, str] = {}
    for cls in ast.walk(src.tree):
        if isinstance(cls, ast.ClassDef):
            for sub in ast.walk(cls):
                class_of.setdefault(id(sub), cls.name)
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        path = dotted(node.func)
        if path is None or path.rsplit(".", 1)[-1] != "Thread":
            continue
        target = None
        for kw in node.keywords:
            if kw.arg == "target":
                target = kw.value
        if target is None and len(node.args) >= 2:
            target = node.args[1]
        if target is None:
            continue
        tgt_path = dotted(target)
        if tgt_path is None:
            continue  # lambda/partial: unresolvable, skip
        out.append((node, tgt_path, class_of.get(id(node))))
    return out


def _resolve_target(src, tgt_path: str, cls_name: str | None):
    """The FunctionDef a thread target names, or None.

    ``self._run`` resolves inside the enclosing class (bases included
    by bare-name search across the file); a bare name resolves to any
    same-named def in the file (worker defs are locally unique in this
    package).
    """
    if tgt_path.startswith(("self.", "cls.")):
        meth = tgt_path.split(".", 1)[1]
        if "." in meth:
            return None
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ) and sub.name == meth:
                        return sub
        return None
    if "." in tgt_path:
        return None  # other-object method: not this file's to prove
    for node in ast.walk(src.tree):
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef)
        ) and node.name == tgt_path:
            return node
    return None


def _check_thread_death(src):
    for call, tgt_path, cls_name in _thread_targets(src):
        fn = _resolve_target(src, tgt_path, cls_name)
        if fn is None:
            continue
        body = fn.body
        if _protected_body(body):
            continue
        yield Finding(
            rule="hygiene-thread-death",
            severity="warning",
            path=src.rel_path,
            line=call.lineno,
            col=call.col_offset,
            message=(
                f"thread target `{tgt_path}` (def at line {fn.lineno}) "
                "can raise outside any try/except — the worker would "
                "die silently, and every invariant it maintains stops "
                "holding with no signal"
            ),
            symbol=f"{(cls_name + '.') if cls_name else ''}{tgt_path}",
        )


def check(project: Project):
    findings: list[Finding] = []
    for src in project.files:
        findings.extend(_check_thread_death(src))
        if src.rel_path.endswith("__init__.py"):
            continue
        used = _used_names(src.tree)
        exported = _exported_names(src.tree)
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Import):
                bindings = [
                    (
                        alias.asname
                        if alias.asname
                        else alias.name.split(".", 1)[0],
                        alias.name,
                    )
                    for alias in node.names
                ]
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                bindings = [
                    (alias.asname or alias.name, alias.name)
                    for alias in node.names
                    if alias.name != "*"
                ]
            else:
                continue
            for local, original in bindings:
                if local in used or local in exported:
                    continue
                findings.append(
                    Finding(
                        rule="hygiene-unused-import",
                        severity="warning",
                        path=src.rel_path,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"`{original}` is imported as `{local}` but "
                            "never used in this module"
                        ),
                        symbol=f"{local}",
                    )
                )
    return findings
