"""``kccap-sanitize``: runtime lockset race detector + lock-order prover.

The static rules (:mod:`.rules_locks`, :mod:`.rules_lockorder`) prove
what the AST can see; this module catches what it structurally cannot —
ordering bugs.  Three instruments, all env-gated behind
``KCCAP_SANITIZE=1`` and all OFF by construction otherwise (the
identity of ``threading.Lock`` and of every instrumented class's
``__getattribute__``/``__setattr__`` is pinned by test when the gate is
closed):

* **lock wrapping** — :func:`install` replaces ``threading.Lock`` /
  ``RLock`` / ``Condition`` with recording wrappers, so every lock
  *created while installed* feeds a per-thread heldset and a global
  lock-order graph.  A cycle in that graph is a potential deadlock,
  reported with the acquisition sites of both orders.
* **Eraser-style lockset race detection** — classes are instrumented
  with recording ``__getattribute__``/``__setattr__``; the monitored
  fields are the *statically inferred* guarded set from
  :func:`..rules_locks.lock_model`, so the static and dynamic provers
  agree on the instrumented surface by construction (and the hammer
  cross-checks the observation both directions).  Each ``(object,
  field)`` runs the classic virgin → exclusive → shared →
  shared-modified state machine with a candidate lockset refined at
  every access; an empty lockset in shared-modified state is a race,
  reported with both threads' sites and held locks.  Only objects
  *born* under instrumentation are tracked (adoption happens when a
  wrapped lock is assigned to an attribute), so pre-existing globals
  whose raw locks are invisible cannot produce false positives.
* **seeded schedule fuzzing** — a counter-based splitmix64 PRNG makes
  perturbation decision *i* a pure function of ``(seed, i)``: targeted
  pre-acquire yields, occasional micro-sleeps, and
  ``sys.setswitchinterval`` jitter drive the chaos suites through
  diverse interleavings, and the same seed replays the same decision
  sequence.  The seed is printed in every report.

Findings flow through the PR 8 workflow: :class:`~.engine.Finding`
identity, inline ``# kccap: lint-ok[...]`` suppression (a site that
admits ``lock-discipline`` admits ``sanitize-race`` too — they are two
provers of one invariant, and the deliberate racy reads are already
marked), and ``LINT_BASELINE.json``.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import _thread
from dataclasses import dataclass

from kubernetesclustercapacity_tpu.analysis.engine import (
    AnalysisResult,
    Baseline,
    Finding,
    parse_suppressions,
)

__all__ = [
    "enabled",
    "install",
    "uninstall",
    "installed",
    "instrument_class",
    "SchedulePRNG",
    "findings",
    "stats",
    "partition",
    "publish_metrics",
    "RACE_RULE",
    "ORDER_RULE",
]

ENV_SWITCH = "KCCAP_SANITIZE"
RACE_RULE = "sanitize-race"
ORDER_RULE = "sanitize-lock-order"

#: Dynamic rule -> static rules whose inline suppression also admits it
#: (one invariant, two provers: a deliberately racy read marked
#: ``lint-ok[lock-discipline]`` is deliberate at runtime too).
RULE_ALIASES = {
    RACE_RULE: ("lock-discipline",),
    ORDER_RULE: ("lock-order",),
}


def enabled() -> bool:
    """The ``KCCAP_SANITIZE=1`` gate — read at install time, never on
    the hot path (when unset, no instrumented code exists at all)."""
    return os.environ.get(ENV_SWITCH, "0").lower() not in ("", "0", "false")


# ---------------------------------------------------------------------------
# Counter-based PRNG: decision i is a pure function of (seed, i).

_MASK64 = (1 << 64) - 1


def _splitmix64(x: int) -> int:
    x = (x + 0x9E3779B97F4A7C15) & _MASK64
    z = x
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _MASK64
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _MASK64
    return z ^ (z >> 31)


class SchedulePRNG:
    """Counter-based randomness: ``at(i)`` depends only on (seed, i),
    so a replay with the same seed takes the same decisions in the
    same order regardless of which thread asks."""

    def __init__(self, seed: int) -> None:
        self.seed = int(seed) & _MASK64
        self._base = _splitmix64(self.seed ^ 0xA5A5A5A5A5A5A5A5)

    def at(self, i: int) -> int:
        return _splitmix64(self._base ^ (i & _MASK64))


class _ScheduleFuzzer:
    """Seeded schedule perturbation at lock-acquire decision points."""

    SWITCH_CHOICES = (1e-6, 5e-6, 2e-5, 1e-4)

    def __init__(self, seed: int) -> None:
        self.prng = SchedulePRNG(seed)
        self._mu = _thread.allocate_lock()
        self._n = 0
        self.decisions = 0
        self.yields = 0
        self.switch_sets = 0

    def pre_acquire(self) -> None:
        with self._mu:
            i = self._n
            self._n += 1
        r = self.prng.at(i)
        self.decisions += 1
        if r % 16 == 0:
            sys.setswitchinterval(
                self.SWITCH_CHOICES[(r >> 8) % len(self.SWITCH_CHOICES)]
            )
            self.switch_sets += 1
        bucket = (r >> 16) % 8
        if bucket == 0:
            # Targeted pre-acquire yield: hand the GIL to whoever is
            # about to race us for this lock.
            time.sleep(0)
            self.yields += 1
        elif bucket == 1:
            time.sleep(1e-5)
            self.yields += 1


# ---------------------------------------------------------------------------
# Eraser field-state machine.

_FS_VIRGIN, _FS_EXCLUSIVE, _FS_SHARED, _FS_SHARED_MOD = range(4)


@dataclass
class _Access:
    tindex: int  # normalized thread index (T1, T2, ... by first event)
    locks: tuple  # held lock names at the access
    site: tuple  # (abs file, line)
    is_write: bool


@dataclass
class _FieldState:
    state: int = _FS_VIRGIN
    owner: int = -1  # tindex of the exclusive thread
    lockset: frozenset | None = None  # candidate lockset (None = all)
    last: _Access | None = None
    reported: bool = False


@dataclass
class _RaceReport:
    label: str  # "ClassName"
    fld: str
    prev: _Access
    cur: _Access


@dataclass
class _OrderEdge:
    a_name: str
    b_name: str
    a_site: tuple  # where a was acquired by the thread that then took b
    b_site: tuple
    tindex: int


# ---------------------------------------------------------------------------
# Lock wrappers.  Created ONLY while installed; fully functional
# delegates so unrelated code (thread startup Events, jax internals)
# keeps working unperturbed.


class _SanLockBase:
    _kind = "lock"

    def __init__(self, inner, san: "_Sanitizer") -> None:
        self._inner = inner
        self._san = san
        self.seq = san._next_seq()
        self.name: str | None = None

    def _display(self) -> str:
        return self.name or f"anon-{self._kind}#{self.seq}"

    def acquire(self, blocking=True, timeout=-1):
        san = self._san
        if san.active:
            san.pre_acquire(self)
        ok = self._inner.acquire(blocking, timeout)
        if ok and san.active:
            san.on_acquired(self)
        return ok

    def release(self):
        self._inner.release()
        san = self._san
        if san.active:
            san.on_released(self)

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self) -> str:
        return f"<sanitized {self._kind} {self._display()} of {self._inner!r}>"


class _SanLock(_SanLockBase):
    """Wrapped ``threading.Lock``.  No ``_release_save``/``_is_owned``
    on purpose: ``threading.Condition`` then falls back to plain
    ``release()``/``acquire()`` — which are exactly our tracked
    methods."""


class _SanRLock(_SanLockBase):
    _kind = "rlock"

    # Condition support: a Condition built on an RLock uses these to
    # fully release around wait().  The heldset must mirror that.
    def _release_save(self):
        san = self._san
        count = san.held_count(self) if san.active else 0
        state = self._inner._release_save()
        if san.active:
            san.on_release_all(self)
        return (count, state)

    def _acquire_restore(self, saved):
        count, state = saved
        self._inner._acquire_restore(state)
        san = self._san
        if san.active:
            san.on_acquire_restore(self, count)

    def _is_owned(self):
        return self._inner._is_owned()


# ---------------------------------------------------------------------------


class _Sanitizer:
    """All mutable detector state, serialized by one raw mutex."""

    def __init__(self, seed: int, fuzz: bool) -> None:
        self.seed = int(seed)
        self._mu = _thread.allocate_lock()
        self.active = True
        self.fuzzer = _ScheduleFuzzer(seed) if fuzz else None
        self._seq = 0
        # Thread identity via a thread-local index, NOT get_ident():
        # the OS reuses idents after a join, and a reused ident would
        # make two threads look like one (masking a race).
        self._tls = threading.local()
        self._tcount = 0
        self.held: dict[int, list] = {}  # T index -> [lock, ...]
        self.held_sites: dict[int, list] = {}  # parallel acquire sites
        self.fields: dict[tuple, _FieldState] = {}
        self.races: list[_RaceReport] = []
        self.order_edges: dict[tuple, _OrderEdge] = {}  # (seqA, seqB)
        self.locks_by_seq: dict[int, _SanLockBase] = {}
        self.tracked: set[int] = set()  # id(obj) of adopted instances
        self.observed_fields: dict[str, set] = {}  # label -> fields seen
        self.observed_locked_writes: dict[str, set] = {}
        self.instrumented: dict[type, tuple] = {}  # cls -> (label, fields)
        self._patched: list = []  # (cls, attr, had_own, original)
        self.field_events = 0
        self.lock_events = 0

    # -- identity helpers --------------------------------------------------
    def _next_seq(self) -> int:
        with self._mu:
            self._seq += 1
            return self._seq

    def _t(self) -> int:
        """This thread's stable index (1-based, by first event); call
        with ``self._mu`` held."""
        idx = getattr(self._tls, "idx", 0)
        if idx == 0:
            self._tcount += 1
            idx = self._tcount
            self._tls.idx = idx
        return idx

    @staticmethod
    def _caller_site() -> tuple:
        f = sys._getframe(2)
        here = __file__
        while f is not None and f.f_code.co_filename == here:
            f = f.f_back
        if f is None:
            return ("<unknown>", 0)
        return (f.f_code.co_filename, f.f_lineno)

    # -- lock events -------------------------------------------------------
    def pre_acquire(self, lock: _SanLockBase) -> None:
        if self.fuzzer is not None:
            self.fuzzer.pre_acquire()

    def on_acquired(self, lock: _SanLockBase) -> None:
        site = self._caller_site()
        with self._mu:
            self.lock_events += 1
            tindex = self._t()
            held = self.held.setdefault(tindex, [])
            sites = self.held_sites.setdefault(tindex, [])
            if not any(h is lock for h in held):
                for h, h_site in zip(held, sites):
                    if h is lock:
                        continue
                    key = (h.seq, lock.seq)
                    if key not in self.order_edges:
                        self.order_edges[key] = _OrderEdge(
                            h._display(),
                            lock._display(),
                            h_site,
                            site,
                            tindex,
                        )
            held.append(lock)
            sites.append(site)
            self.locks_by_seq.setdefault(lock.seq, lock)

    def on_released(self, lock: _SanLockBase) -> None:
        with self._mu:
            tindex = self._t()
            held = self.held.get(tindex, [])
            sites = self.held_sites.get(tindex, [])
            for i in range(len(held) - 1, -1, -1):
                if held[i] is lock:
                    del held[i]
                    del sites[i]
                    break

    def held_count(self, lock: _SanLockBase) -> int:
        with self._mu:
            tindex = self._t()
            return sum(1 for h in self.held.get(tindex, ()) if h is lock)

    def on_release_all(self, lock: _SanLockBase) -> None:
        with self._mu:
            tindex = self._t()
            held = self.held.get(tindex, [])
            sites = self.held_sites.get(tindex, [])
            keep = [(h, s) for h, s in zip(held, sites) if h is not lock]
            self.held[tindex] = [h for h, _ in keep]
            self.held_sites[tindex] = [s for _, s in keep]

    def on_acquire_restore(self, lock: _SanLockBase, count: int) -> None:
        site = self._caller_site()
        with self._mu:
            tindex = self._t()
            held = self.held.setdefault(tindex, [])
            sites = self.held_sites.setdefault(tindex, [])
            for _ in range(max(count, 1)):
                held.append(lock)
                sites.append(site)

    # -- field events ------------------------------------------------------
    def adopt(self, obj) -> None:
        with self._mu:
            self.tracked.add(id(obj))

    def on_field_access(self, obj, label: str, fld: str, is_write: bool):
        site = self._caller_site()
        with self._mu:
            if id(obj) not in self.tracked:
                return
            self.field_events += 1
            tindex = self._t()
            held = self.held.get(tindex, ())
            lock_names = tuple(
                dict.fromkeys(h._display() for h in held)
            )
            self.observed_fields.setdefault(label, set()).add(fld)
            if is_write and lock_names:
                self.observed_locked_writes.setdefault(label, set()).add(fld)
            access = _Access(tindex, lock_names, site, is_write)
            key = (id(obj), label, fld)
            fs = self.fields.get(key)
            if fs is None:
                fs = _FieldState()
                self.fields[key] = fs
            if fs.state == _FS_VIRGIN:
                fs.state = _FS_EXCLUSIVE
                fs.owner = tindex
            elif fs.state == _FS_EXCLUSIVE:
                if tindex != fs.owner:
                    # Classic Eraser: promote to shared-modified only on
                    # a shared-era WRITE.  (An owner-era write followed
                    # by read-only sharing is the init-handoff pattern —
                    # benign by publication, and exactly the false
                    # positive the original paper documents avoiding.)
                    fs.state = _FS_SHARED_MOD if is_write else _FS_SHARED
                    fs.lockset = frozenset(lock_names)
            else:
                if is_write:
                    fs.state = _FS_SHARED_MOD
                assert fs.lockset is not None
                fs.lockset = fs.lockset & frozenset(lock_names)
            if (
                fs.state == _FS_SHARED_MOD
                and fs.lockset is not None
                and not fs.lockset
                and not fs.reported
            ):
                fs.reported = True
                prev = fs.last or access
                self.races.append(_RaceReport(label, fld, prev, access))
            fs.last = access

    # -- class instrumentation ---------------------------------------------
    def instrument_class(self, cls: type, fields, label: str) -> None:
        if cls in self.instrumented:
            return
        monitored = frozenset(fields)
        self.instrumented[cls] = (label, monitored)
        san = self
        orig_get = cls.__getattribute__
        orig_set = cls.__setattr__

        def __getattribute__(self_, name):
            value = orig_get(self_, name)
            if name in monitored and san.active:
                san.on_field_access(self_, label, name, False)
            return value

        def __setattr__(self_, name, value):
            if san.active:
                if isinstance(value, _SanLockBase):
                    if value.name is None:
                        value.name = f"{label}.{name}"
                    san.adopt(self_)
                if name in monitored:
                    san.on_field_access(self_, label, name, True)
            orig_set(self_, name, value)

        for attr, fn in (
            ("__getattribute__", __getattribute__),
            ("__setattr__", __setattr__),
        ):
            had_own = attr in cls.__dict__
            self._patched.append((cls, attr, had_own, cls.__dict__.get(attr)))
            setattr(cls, attr, fn)

    def unpatch_classes(self) -> None:
        for cls, attr, had_own, original in reversed(self._patched):
            if had_own:
                setattr(cls, attr, original)
            else:
                try:
                    delattr(cls, attr)
                except AttributeError:
                    pass
        self._patched.clear()


# ---------------------------------------------------------------------------
# Install / uninstall: the only code that touches process-global state.

_STATE: _Sanitizer | None = None
_SAVED: dict | None = None


def installed() -> bool:
    return _STATE is not None


def install(*, seed: int = 0, fuzz: bool = True, classes=()) -> None:
    """Arm the sanitizer: patch lock construction, remember the switch
    interval, and instrument ``classes`` (iterable of ``(cls, fields,
    label)``).  Requires ``KCCAP_SANITIZE=1`` — the gate exists so no
    production path can arm instrumentation by accident."""
    global _STATE, _SAVED
    if not enabled():
        raise RuntimeError(
            f"sanitizer is env-gated: set {ENV_SWITCH}=1 to install"
        )
    if _STATE is not None:
        raise RuntimeError("sanitizer already installed (uninstall first)")
    san = _Sanitizer(seed, fuzz)
    saved = {
        "Lock": threading.Lock,
        "RLock": threading.RLock,
        "Condition": threading.Condition,
        "switchinterval": sys.getswitchinterval(),
    }
    orig_lock = threading.Lock
    orig_rlock = threading.RLock
    orig_condition = threading.Condition

    def Lock():
        return _SanLock(orig_lock(), san)

    def RLock():
        return _SanRLock(orig_rlock(), san)

    def Condition(lock=None):
        if lock is None:
            lock = RLock()
        return orig_condition(lock)

    threading.Lock = Lock
    threading.RLock = RLock
    threading.Condition = Condition
    _STATE = san
    _SAVED = saved
    for cls, fields, label in classes:
        san.instrument_class(cls, fields, label)


def instrument_class(cls: type, fields, label: str | None = None) -> None:
    """Monitor ``fields`` on ``cls`` (post-install registration)."""
    if _STATE is None:
        raise RuntimeError("sanitizer is not installed")
    _STATE.instrument_class(cls, fields, label or cls.__name__)


def uninstall() -> None:
    """Restore every patched surface.  Idempotent — safe as a test
    teardown even when nothing was installed.  Wrapped locks created
    during the window keep working afterwards (they delegate to a real
    primitive and their sanitizer is deactivated)."""
    global _STATE, _SAVED
    san, saved = _STATE, _SAVED
    _STATE, _SAVED = None, None
    if san is None:
        return
    san.active = False
    san.unpatch_classes()
    if saved is not None:
        threading.Lock = saved["Lock"]
        threading.RLock = saved["RLock"]
        threading.Condition = saved["Condition"]
        sys.setswitchinterval(saved["switchinterval"])


# ---------------------------------------------------------------------------
# Reporting.


def _rel(path: str, repo_root: str | None) -> str:
    if repo_root:
        try:
            rel = os.path.relpath(path, repo_root)
        except ValueError:
            return path.replace(os.sep, "/")
        if not rel.startswith(".."):
            return rel.replace(os.sep, "/")
    return path.replace(os.sep, "/")


def _fmt_site(site: tuple, repo_root: str | None) -> str:
    return f"{_rel(site[0], repo_root)}:{site[1]}"


def _fmt_locks(locks: tuple) -> str:
    if not locks:
        return "no locks held"
    return "holding {%s}" % ", ".join(f"`{n}`" for n in locks)


def _race_findings(san: _Sanitizer, repo_root: str | None):
    out = []
    seen = set()
    for r in san.races:
        verb_prev = "wrote" if r.prev.is_write else "read"
        verb_cur = "wrote" if r.cur.is_write else "read"
        path = _rel(r.cur.site[0], repo_root)
        line = r.cur.site[1]
        symbol = f"{r.label}.{r.fld}"
        dedup = (symbol, path, line, r.prev.site)
        if dedup in seen:
            continue
        seen.add(dedup)
        out.append(
            Finding(
                rule=RACE_RULE,
                severity="error",
                path=path,
                line=line,
                col=0,
                message=(
                    f"lockset race on `{symbol}`: "
                    f"T{r.prev.tindex} {verb_prev} at "
                    f"{_fmt_site(r.prev.site, repo_root)} "
                    f"({_fmt_locks(r.prev.locks)}); "
                    f"T{r.cur.tindex} {verb_cur} at "
                    f"{_fmt_site(r.cur.site, repo_root)} "
                    f"({_fmt_locks(r.cur.locks)}); candidate lockset is "
                    f"empty [seed {san.seed}]"
                ),
                symbol=symbol,
            )
        )
    return out


def _cycle_findings(san: _Sanitizer, repo_root: str | None):
    # Successor map over lock seqs, then: an edge (a, b) where b
    # reaches a sits on a cycle.
    succ: dict[int, set[int]] = {}
    for a, b in san.order_edges:
        succ.setdefault(a, set()).add(b)
        succ.setdefault(b, set())
    reach_cache: dict[int, set[int]] = {}

    def reach(start: int) -> set[int]:
        hit = reach_cache.get(start)
        if hit is not None:
            return hit
        seen: set[int] = set()
        stack = [start]
        while stack:
            cur = stack.pop()
            for nxt in succ.get(cur, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(nxt)
        reach_cache[start] = seen
        return seen

    out = []
    for (a, b), edge in sorted(
        san.order_edges.items(),
        key=lambda kv: (kv[1].a_name, kv[1].b_name),
    ):
        if a not in reach(b):
            continue
        opposing = None
        for (x, y), other in san.order_edges.items():
            if x == b and a in reach(y) | {y}:
                opposing = other
                break
        msg = (
            f"lock-order inversion observed: T{edge.tindex} acquired "
            f"`{edge.b_name}` at {_fmt_site(edge.b_site, repo_root)} "
            f"while holding `{edge.a_name}` (taken at "
            f"{_fmt_site(edge.a_site, repo_root)})"
        )
        if opposing is not None:
            msg += (
                f"; the opposing order `{opposing.a_name}` -> "
                f"`{opposing.b_name}` was taken by T{opposing.tindex} at "
                f"{_fmt_site(opposing.b_site, repo_root)}"
            )
        msg += f" [seed {san.seed}]"
        out.append(
            Finding(
                rule=ORDER_RULE,
                severity="error",
                path=_rel(edge.b_site[0], repo_root),
                line=edge.b_site[1],
                col=0,
                message=msg,
                symbol=f"{edge.a_name}->{edge.b_name}",
            )
        )
    return out


def findings(repo_root: str | None = None) -> list:
    """Everything the currently-installed sanitizer observed, as engine
    findings in deterministic order (snapshot via :func:`current` and
    use :func:`findings_of` to report after an uninstall)."""
    san = _STATE
    if san is None:
        raise RuntimeError("sanitizer is not installed")
    return findings_of(san, repo_root)


def findings_of(san: _Sanitizer, repo_root: str | None = None) -> list:
    out = _race_findings(san, repo_root) + _cycle_findings(san, repo_root)
    out.sort(key=lambda f: (f.path, f.line, f.rule, f.symbol, f.message))
    return out


def stats() -> dict:
    """Counters for the CLI/doctor/metrics surfaces."""
    san = _STATE
    if san is None:
        raise RuntimeError("sanitizer is not installed")
    return stats_of(san)


def stats_of(san: _Sanitizer) -> dict:
    fz = san.fuzzer
    return {
        "seed": san.seed,
        "threads_seen": san._tcount,
        "locks_created": san._seq,
        "lock_events": san.lock_events,
        "field_events": san.field_events,
        "order_edges": len(san.order_edges),
        "races": len(san.races),
        "instrumented_classes": len(san.instrumented),
        "schedule_decisions": fz.decisions if fz else 0,
        "schedule_yields": fz.yields if fz else 0,
        "switch_sets": fz.switch_sets if fz else 0,
        "observed_fields": {
            label: sorted(flds)
            for label, flds in sorted(san.observed_fields.items())
        },
        "observed_locked_writes": {
            label: sorted(flds)
            for label, flds in sorted(san.observed_locked_writes.items())
        },
    }


def current() -> _Sanitizer | None:
    """The installed sanitizer (None when the gate is closed) — the
    hammer snapshots it before uninstalling."""
    return _STATE


def partition(
    found: list,
    baseline: Baseline,
    repo_root: str,
) -> AnalysisResult:
    """The PR 8 workflow for dynamic findings: inline ``lint-ok``
    markers at the access site (rule aliases honored) and the shared
    ``LINT_BASELINE.json``."""
    sup_cache: dict[str, dict] = {}

    def suppressions_for(path: str) -> dict:
        hit = sup_cache.get(path)
        if hit is None:
            abs_path = os.path.join(repo_root, path)
            try:
                with open(abs_path, encoding="utf-8") as fh:
                    hit = parse_suppressions(fh.read())
            except OSError:
                hit = {}
            sup_cache[path] = hit
        return hit

    live: list = []
    suppressed: list = []
    baselined: list = []
    for f in found:
        admitted = suppressions_for(f.path).get(f.line, ())
        rules = (f.rule,) + RULE_ALIASES.get(f.rule, ())
        if "*" in admitted or any(r in admitted for r in rules):
            suppressed.append(f)
        elif baseline.matches(f):
            baselined.append(f)
        else:
            live.append(f)
    return AnalysisResult(live, suppressed, baselined)


def publish_metrics(st: dict, result: AnalysisResult) -> None:
    """Mirror one sanitize run into the process registry (no-op under
    ``KCCAP_TELEMETRY=0``)."""
    from kubernetesclustercapacity_tpu.telemetry.metrics import (
        REGISTRY,
        enabled as _telemetry_enabled,
    )

    if not _telemetry_enabled():
        return
    REGISTRY.counter(
        "kccap_sanitize_runs_total",
        "Completed sanitizer runs (install → hammer → report).",
    ).inc()
    REGISTRY.counter(
        "kccap_sanitize_races_total",
        "Candidate lockset races observed across sanitizer runs "
        "(suppressed/baselined included — the detector's raw yield).",
    ).inc(st["races"])
    REGISTRY.counter(
        "kccap_sanitize_lock_order_cycles_total",
        "Observed lock-order inversion edges across sanitizer runs.",
    ).inc(sum(1 for f in result.findings + result.suppressed +
              result.baselined if f.rule == ORDER_RULE))
    REGISTRY.gauge(
        "kccap_sanitize_instrumented_classes",
        "Classes under attribute instrumentation in the last run.",
    ).set(st["instrumented_classes"])
    REGISTRY.counter(
        "kccap_sanitize_schedule_decisions_total",
        "Schedule-fuzzer decision points taken (yields + switch-"
        "interval jitter), across runs.",
    ).inc(st["schedule_decisions"])
