"""``kccap-lint``: the console entry point for the static analyzer.

Usage::

    kccap-lint                      # analyze the installed package
    kccap-lint path/to/package      # analyze an arbitrary package dir
    kccap-lint --json               # machine-readable findings artifact
    kccap-lint --write-baseline     # accept current findings as baseline
    kccap-lint --rules jit-purity,lock-discipline
    kccap-lint --no-baseline        # ignore the checked-in baseline
    kccap-lint --diff-baseline      # CI mode: ONLY new findings, no recap

Exit codes: ``0`` clean (no non-baselined findings), ``1`` findings,
``2`` usage/configuration error — so the tier-1 test, a pre-commit hook
and a CI job can all gate on the same invocation.  ``--diff-baseline``
prints nothing but the findings absent from the baseline (one per
line) and exits 1 on any — no re-listing of accepted history, so a CI
log is empty exactly when the gate passes.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from kubernetesclustercapacity_tpu.analysis.engine import (
    Analyzer,
    Baseline,
    Project,
)

__all__ = ["main", "run"]

BASELINE_FILENAME = "LINT_BASELINE.json"


def _default_package_dir() -> str:
    # The package this module ships inside — works both from a checkout
    # and an installed wheel.
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kccap-lint",
        description=(
            "Project-native static analysis: jit-purity prover, "
            "lock-discipline checker, lock-order prover, "
            "surface-conformance walks."
        ),
    )
    p.add_argument(
        "package",
        nargs="?",
        default=None,
        help="package directory to analyze (default: the installed "
        "kubernetesclustercapacity_tpu package)",
    )
    p.add_argument(
        "--readme",
        default=None,
        help="README the surface rules check against "
        "(default: <repo-root>/README.md)",
    )
    p.add_argument(
        "--baseline",
        default=None,
        help=f"baseline file (default: <repo-root>/{BASELINE_FILENAME})",
    )
    p.add_argument(
        "--no-baseline",
        action="store_true",
        help="report every finding, ignoring the baseline",
    )
    p.add_argument(
        "--diff-baseline",
        action="store_true",
        dest="diff_baseline",
        help="print only findings NOT in the baseline (no summary, no "
        "recap of accepted history) and exit 1 on any — the CI/tier-1 "
        "gate mode",
    )
    p.add_argument(
        "--write-baseline",
        action="store_true",
        help="accept the current findings into the baseline file and exit 0",
    )
    p.add_argument(
        "--rules",
        default=None,
        help="comma-separated rule families to run "
        "(jit-purity,lock-discipline,lock-order,surface,hygiene; "
        "default: all)",
    )
    p.add_argument(
        "--json",
        action="store_true",
        dest="as_json",
        help="emit the machine-readable findings artifact on stdout",
    )
    return p


def run(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    package_dir = os.path.abspath(args.package or _default_package_dir())
    try:
        project = Project(package_dir, readme_path=args.readme)
    except (FileNotFoundError, SyntaxError) as e:
        print(f"kccap-lint: {e}", file=sys.stderr)
        return 2

    baseline_path = args.baseline or os.path.join(
        project.repo_root, BASELINE_FILENAME
    )
    try:
        baseline = (
            Baseline() if args.no_baseline else Baseline.load(baseline_path)
        )
    except (ValueError, json.JSONDecodeError) as e:
        print(f"kccap-lint: bad baseline {baseline_path}: {e}", file=sys.stderr)
        return 2

    rules = None
    if args.rules:
        rules = tuple(r.strip() for r in args.rules.split(",") if r.strip())
    try:
        analyzer = Analyzer(project, rules=rules, baseline=baseline)
    except ValueError as e:
        print(f"kccap-lint: {e}", file=sys.stderr)
        return 2
    result = analyzer.run()

    if args.write_baseline:
        merged = Baseline.from_findings(
            result.findings, history=baseline.history
        )
        merged.entries |= baseline.entries
        merged.save(baseline_path)
        print(
            f"kccap-lint: baseline updated ({len(result.findings)} finding(s) "
            f"accepted) -> {baseline_path}"
        )
        return 0

    if args.diff_baseline:
        # CI mode: the log is empty exactly when the gate passes.
        for f in result.findings:
            print(f.render())
        return 0 if result.clean else 1

    if args.as_json:
        print(json.dumps(result.to_json(), indent=2))
    else:
        for f in result.findings:
            print(f.render())
        n, s, b = (
            len(result.findings),
            len(result.suppressed),
            len(result.baselined),
        )
        print(
            f"kccap-lint: {n} finding(s), {s} suppressed inline, "
            f"{b} baselined, over {len(project.files)} file(s)"
        )
    return 0 if result.clean else 1


def main() -> None:  # console_scripts entry
    sys.exit(run())


if __name__ == "__main__":
    main()
