"""``kccap-lint``: project-native static analysis.

The invariants this package proves were previously only *dynamically*
pinned — "``KCCAP_TELEMETRY=0`` means zero registry calls in jitted
code" was a sampled property (a few tests import a few kernels), the
thread-safety of the registry/cache/batcher/timeline classes was a
convention, and the metric-name walk in ``tests/test_metric_names.py``
was the lone *textual* conformance check.  Here the same invariants are
theorems over the AST, checked on every tier-1 run:

* **jit-purity** (:mod:`.rules_jit`) — an intra-package call graph
  rooted at every ``jax.jit``/``pjit``/``pallas_call`` function proves
  no telemetry-registry call, lock acquisition, I/O, ``time.*``/
  ``random.*`` use, ``print``, bare-numpy-on-traced-array op or
  ``float()/int()/bool()`` coercion of a traced value is reachable
  from inside a jitted region.
* **lock-discipline** (:mod:`.rules_locks`) — the guarded-field set of
  each threaded class is inferred from its ``with self._lock:`` blocks
  (in-place container mutations count; ctor-proven lock attrs carry
  through inheritance), and every read/write of a guarded field
  outside the lock is flagged.  The inference is exposed as
  :func:`.rules_locks.lock_model` — the single model both provers use.
* **lock-order** (:mod:`.rules_lockorder`) — the static lock-order
  graph (lexical nesting + calls made while holding a lock, closed
  over the call graph) must be acyclic; a cycle is a deadlock waiting
  for its schedule, reported at both orders' exact sites.
* **surface conformance** (:mod:`.rules_surface`) — every ``kccap_``
  metric literal, ``KCCAP_*`` env var, server op and CLI flag must be
  README-documented (and ops client-reachable): the generalized,
  engine-native form of the metric-name walk.
* **hygiene** (:mod:`.rules_hygiene`) — a pyflakes-lite unused-import
  walk, plus the silent-thread-death rule: every resolvable
  ``threading.Thread`` target must be try-protected (or
  ``utils.threads.supervised``-wrapped) so no worker dies silently.

The *lint* rules are AST-based: the analyzer never imports the code
under analysis, so a broken module cannot crash the lint and lint
findings cannot depend on the host's backends.  The *sanitizer*
(:mod:`.sanitize`, ``kccap-sanitize``) is the deliberate runtime
complement — an env-gated (``KCCAP_SANITIZE=1``) Eraser-style lockset
race detector, observed lock-order prover, and seeded schedule fuzzer
whose hammer (:mod:`.hammer`) certifies the package's threaded classes
under tier-1.  Findings from BOTH flow through one workflow: severity +
``file:line``, ``# kccap: lint-ok[rule]`` inline suppression, and the
checked-in ``LINT_BASELINE.json``.  ``kccap-lint --json`` /
``kccap-sanitize --json`` emit the machine-readable forms;
``kccap-lint --diff-baseline`` is the CI mode that prints only findings
beyond the baseline.
"""

from kubernetesclustercapacity_tpu.analysis.engine import (
    Analyzer,
    AnalysisResult,
    Baseline,
    Finding,
    Project,
)

__all__ = [
    "Analyzer",
    "AnalysisResult",
    "Baseline",
    "Finding",
    "Project",
]
