"""``kccap-lint``: project-native static analysis.

The invariants this package proves were previously only *dynamically*
pinned — "``KCCAP_TELEMETRY=0`` means zero registry calls in jitted
code" was a sampled property (a few tests import a few kernels), the
thread-safety of the registry/cache/batcher/timeline classes was a
convention, and the metric-name walk in ``tests/test_metric_names.py``
was the lone *textual* conformance check.  Here the same invariants are
theorems over the AST, checked on every tier-1 run:

* **jit-purity** (:mod:`.rules_jit`) — an intra-package call graph
  rooted at every ``jax.jit``/``pjit``/``pallas_call`` function proves
  no telemetry-registry call, lock acquisition, I/O, ``time.*``/
  ``random.*`` use, ``print``, bare-numpy-on-traced-array op or
  ``float()/int()/bool()`` coercion of a traced value is reachable
  from inside a jitted region.
* **lock-discipline** (:mod:`.rules_locks`) — the guarded-field set of
  each threaded class is inferred from its ``with self._lock:`` blocks,
  and every read/write of a guarded field outside the lock is flagged.
* **surface conformance** (:mod:`.rules_surface`) — every ``kccap_``
  metric literal, ``KCCAP_*`` env var, server op and CLI flag must be
  README-documented (and ops client-reachable): the generalized,
  engine-native form of the metric-name walk.
* **hygiene** (:mod:`.rules_hygiene`) — a pyflakes-lite unused-import
  walk so the tree stays clean even where ``ruff`` is not installed.

Everything is AST-based: the analyzer never imports the code under
analysis, so a broken module cannot crash the lint and lint findings
cannot depend on the host's backends.  Findings carry severity +
``file:line``; ``# kccap: lint-ok[rule]`` suppresses inline, and a
checked-in baseline (``LINT_BASELINE.json``) makes adoption
incremental.  ``kccap-lint --json`` emits the machine-readable form.
"""

from kubernetesclustercapacity_tpu.analysis.engine import (
    Analyzer,
    AnalysisResult,
    Baseline,
    Finding,
    Project,
)

__all__ = [
    "Analyzer",
    "AnalysisResult",
    "Baseline",
    "Finding",
    "Project",
]
