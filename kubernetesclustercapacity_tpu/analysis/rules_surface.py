"""Surface-conformance walks: every operator-visible name is documented.

The generalized, engine-native form of ``tests/test_metric_names.py``:
an operator greps the README for anything a scrape, an env, a wire op
or a CLI flag can surface — so everything the *code* can emit must be
in the README, and wire ops must be reachable from the bundled client.
Four walks, each its own rule id (suppressions/baselines key on them):

* ``surface-metric`` — every ``"kccap_..."`` string literal is
  ``kccap_``-prefixed snake_case AND matched by a README token (the
  README's ``kccap_client_*_total`` glob / ``{a,b}`` alternation
  shorthand is honored);
* ``surface-env`` — every ``KCCAP_*`` literal appears in the README's
  configuration table;
* ``surface-op`` — every op in the server's ``_KNOWN_OPS`` is
  README-documented and client-reachable (named in the client source);
* ``surface-flag`` — every ``add_argument("-flag")`` literal in the
  package is README-documented;
* ``surface-span`` — every field keyword a ``span(...)`` emission call
  passes (including ``**{...}`` dict-splat keys) is in the documented
  ``SPAN_FIELDS`` vocabulary, the same way phase names are pinned to
  ``phases.PHASES`` — trace consumers grep spans by field name, so an
  off-vocabulary field is a silently unqueryable one.
"""

from __future__ import annotations

import ast
import re

from kubernetesclustercapacity_tpu.analysis.engine import Finding, Project

__all__ = ["check", "doc_patterns"]

_METRIC_RE = re.compile(r"""["'](kccap_[A-Za-z0-9_]+)["']""")
_SNAKE_RE = re.compile(r"kccap_[a-z0-9]+(_[a-z0-9]+)*")
_DOC_TOKEN_RE = re.compile(r"kccap_[A-Za-z0-9_*{},|]+")
_ENV_RE = re.compile(r"KCCAP_[A-Z][A-Z0-9_]*")


def doc_patterns(readme_text: str) -> list[re.Pattern]:
    """README ``kccap_*`` tokens -> matchers, honoring the observability
    table's glob (``*``) and alternation (``{a,b}``) shorthand.  Same
    grammar the metric-name test pinned; kept here so the engine and the
    test cannot drift apart."""
    patterns: list[re.Pattern] = []
    for tok in set(_DOC_TOKEN_RE.findall(readme_text)):
        plain = tok.split("{", 1)[0].rstrip("_*")
        if plain:
            patterns.append(re.compile(re.escape(plain)))
        out, i, ok = "", 0, True
        while i < len(tok):
            c = tok[i]
            if c == "*":
                out += "[a-z0-9_]*"
            elif c == "{":
                j = tok.find("}", i)
                if j == -1 or "," not in tok[i:j]:
                    ok = False
                    break
                alts = tok[i + 1 : j].split(",")
                out += "(" + "|".join(re.escape(a) for a in alts) + ")"
                i = j
            elif c in "},|":
                ok = False
                break
            else:
                out += re.escape(c)
            i += 1
        if ok:
            patterns.append(re.compile(out))
    return patterns


def _line_of(text: str, offset: int) -> int:
    return text.count("\n", 0, offset) + 1


def _word_in(text: str, word: str) -> bool:
    return re.search(
        rf"(?<![A-Za-z0-9_\-]){re.escape(word)}(?![A-Za-z0-9_\-])", text
    ) is not None


def _iter_string_sites(src, pattern: re.Pattern):
    for m in pattern.finditer(src.text):
        yield m.group(0) if m.lastindex is None else m.group(1), _line_of(
            src.text, m.start()
        )


def _check_metrics(project: Project, readme: str):
    patterns = doc_patterns(readme)
    for src in project.files:
        for name, line in _iter_string_sites(src, _METRIC_RE):
            if not _SNAKE_RE.fullmatch(name):
                yield Finding(
                    rule="surface-metric",
                    severity="error",
                    path=src.rel_path,
                    line=line,
                    col=0,
                    message=(
                        f"metric `{name}` is not kccap_-prefixed "
                        "snake_case"
                    ),
                    symbol=name,
                )
            elif not any(p.fullmatch(name) for p in patterns):
                yield Finding(
                    rule="surface-metric",
                    severity="error",
                    path=src.rel_path,
                    line=line,
                    col=0,
                    message=(
                        f"metric `{name}` is registered here but missing "
                        "from the README observability table"
                    ),
                    symbol=name,
                )


def _check_envs(project: Project, readme: str):
    for src in project.files:
        for name, line in _iter_string_sites(src, _ENV_RE):
            if not _word_in(readme, name):
                yield Finding(
                    rule="surface-env",
                    severity="error",
                    path=src.rel_path,
                    line=line,
                    col=0,
                    message=(
                        f"env var `{name}` is read here but missing from "
                        "the README configuration table"
                    ),
                    symbol=name,
                )


def _known_ops(src) -> list[tuple[str, int]]:
    """The ``_KNOWN_OPS = frozenset({...})`` literal in the server."""
    out: list[tuple[str, int]] = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Assign):
            continue
        names = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if "_KNOWN_OPS" not in names:
            continue
        for sub in ast.walk(node.value):
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                out.append((sub.value, node.lineno))
    return out


def _check_ops(project: Project, readme: str):
    server = project.file_by_module_tail("service", "server.py")
    client = project.file_by_module_tail("service", "client.py")
    if server is None:
        return
    client_text = client.text if client is not None else ""
    for op, line in _known_ops(server):
        if not _word_in(readme, op):
            yield Finding(
                rule="surface-op",
                severity="error",
                path=server.rel_path,
                line=line,
                col=0,
                message=(
                    f"server op `{op}` is routed here but not documented "
                    "in the README"
                ),
                symbol=op,
            )
        reachable = (
            f'"{op}"' in client_text
            or f"'{op}'" in client_text
            or f"def {op}(" in client_text
        )
        if not reachable:
            yield Finding(
                rule="surface-op",
                severity="error",
                path=server.rel_path,
                line=line,
                col=0,
                message=(
                    f"server op `{op}` has no reachable client surface "
                    "(no literal or method in service/client.py)"
                ),
                symbol=f"{op}:client",
            )


def _check_flags(project: Project, readme: str):
    for src in project.files:
        for node in ast.walk(src.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and node.args[0].value.startswith("-")
            ):
                continue
            flag = node.args[0].value
            if not _word_in(readme, flag):
                yield Finding(
                    rule="surface-flag",
                    severity="error",
                    path=src.rel_path,
                    line=node.lineno,
                    col=node.col_offset,
                    message=(
                        f"CLI flag `{flag}` is declared here but not "
                        "documented in the README"
                    ),
                    symbol=flag,
                )


def _span_call_fields(node: ast.Call):
    """The field-name literals one ``span(...)`` call passes: explicit
    keywords plus every string key of a ``**{...}`` splat (the
    conditional-field idiom ``**({"error": e} if e else {})``)."""
    for kw in node.keywords:
        if kw.arg is not None:
            yield kw.arg, kw.value.lineno if hasattr(kw.value, "lineno") else node.lineno
        else:
            for sub in ast.walk(kw.value):
                if isinstance(sub, ast.Dict):
                    for key in sub.keys:
                        if isinstance(key, ast.Constant) and isinstance(
                            key.value, str
                        ):
                            yield key.value, key.lineno


def _check_spans(project: Project):
    from kubernetesclustercapacity_tpu.telemetry.tracectx import SPAN_FIELDS

    for src in project.files:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            is_span = (
                isinstance(func, ast.Name) and func.id == "span"
            ) or (
                isinstance(func, ast.Attribute) and func.attr == "span"
            )
            if not is_span:
                continue
            for field, line in _span_call_fields(node):
                if field not in SPAN_FIELDS:
                    yield Finding(
                        rule="surface-span",
                        severity="error",
                        path=src.rel_path,
                        line=line,
                        col=node.col_offset,
                        message=(
                            f"span field `{field}` is outside the "
                            "documented SPAN_FIELDS vocabulary "
                            "(telemetry/tracectx.py) — emission would "
                            "silently drop it"
                        ),
                        symbol=field,
                    )


def check(project: Project):
    readme = project.readme_text()
    findings: list[Finding] = []
    findings.extend(_check_metrics(project, readme))
    findings.extend(_check_envs(project, readme))
    findings.extend(_check_ops(project, readme))
    findings.extend(_check_flags(project, readme))
    findings.extend(_check_spans(project))
    return findings
