"""``kccap-sanitize``: the console entry point for the dynamic sanitizer.

Usage::

    kccap-sanitize                      # static lock-order + seeded hammer
    kccap-sanitize --seeds 3            # hammer under 3 seeds (0,1,2)
    kccap-sanitize --seed 42            # one specific seed (repro mode)
    kccap-sanitize --threads 16 --iters 40
    kccap-sanitize --static-only        # just the AST lock-order prover
    kccap-sanitize --json               # machine-readable artifact
    kccap-sanitize --no-baseline        # ignore LINT_BASELINE.json

Exit codes mirror ``kccap-lint``: ``0`` clean, ``1`` unsuppressed
findings, ``2`` usage/configuration error.  Every line of dynamic
output carries its seed — paste the seed back via ``--seed`` to replay
the exact perturbation decision sequence.

Unlike ``kccap-lint``, this tool IMPORTS and RUNS the package (that is
the point); it arms the ``KCCAP_SANITIZE`` gate itself for the
duration of the run.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

__all__ = ["main", "run"]

BASELINE_FILENAME = "LINT_BASELINE.json"


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kccap-sanitize",
        description=(
            "Runtime lockset race detector, lock-order deadlock prover "
            "and seeded schedule fuzzer over the package's threaded "
            "classes."
        ),
    )
    p.add_argument(
        "package",
        nargs="?",
        default=None,
        help="package directory to certify (default: the installed "
        "kubernetesclustercapacity_tpu package)",
    )
    p.add_argument(
        "--seeds",
        type=int,
        default=3,
        help="number of hammer seeds to run (0..N-1; default 3)",
    )
    p.add_argument(
        "--seed",
        type=int,
        default=None,
        help="run exactly ONE seed (replay mode: the same seed replays "
        "the same schedule-perturbation decisions)",
    )
    p.add_argument(
        "--threads",
        type=int,
        default=16,
        help="concurrent workers per hammered class (default 16)",
    )
    p.add_argument(
        "--iters",
        type=int,
        default=40,
        help="ops per worker per class (default 40)",
    )
    p.add_argument(
        "--static-only",
        action="store_true",
        dest="static_only",
        help="run only the AST lock-order prover (no imports, no "
        "threads — the kccap-lint subset)",
    )
    p.add_argument(
        "--no-fuzz",
        action="store_true",
        dest="no_fuzz",
        help="disable schedule perturbation (lockset analysis only)",
    )
    p.add_argument(
        "--baseline",
        default=None,
        help=f"baseline file (default: <repo-root>/{BASELINE_FILENAME})",
    )
    p.add_argument(
        "--no-baseline",
        action="store_true",
        help="report every finding, ignoring the baseline",
    )
    p.add_argument(
        "--json",
        action="store_true",
        dest="as_json",
        help="emit the machine-readable findings artifact on stdout",
    )
    return p


def run(argv=None) -> int:
    from kubernetesclustercapacity_tpu.analysis import sanitize
    from kubernetesclustercapacity_tpu.analysis.engine import (
        Analyzer,
        Baseline,
        Project,
    )

    args = _build_parser().parse_args(argv)
    if args.threads < 1 or args.iters < 1 or args.seeds < 1:
        print(
            "kccap-sanitize: --threads/--iters/--seeds must be >= 1",
            file=sys.stderr,
        )
        return 2
    package_dir = os.path.abspath(
        args.package
        or os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    try:
        project = Project(package_dir)
    except (FileNotFoundError, SyntaxError) as e:
        print(f"kccap-sanitize: {e}", file=sys.stderr)
        return 2
    baseline_path = args.baseline or os.path.join(
        project.repo_root, BASELINE_FILENAME
    )
    try:
        baseline = (
            Baseline() if args.no_baseline else Baseline.load(baseline_path)
        )
    except (ValueError, json.JSONDecodeError) as e:
        print(
            f"kccap-sanitize: bad baseline {baseline_path}: {e}",
            file=sys.stderr,
        )
        return 2

    # -- static half: the AST lock-order prover (shared with kccap-lint).
    static = Analyzer(project, rules=("lock-order",), baseline=baseline).run()

    # -- dynamic half: the seeded hammer.
    seeds = [args.seed] if args.seed is not None else list(range(args.seeds))
    runs = []
    dyn_live: list = []
    dyn_suppressed: list = []
    dyn_baselined: list = []
    if not args.static_only:
        from kubernetesclustercapacity_tpu.analysis import hammer

        os.environ.setdefault(sanitize.ENV_SWITCH, "1")
        for seed in seeds:
            try:
                found, st = hammer.run(
                    seed=seed,
                    threads=args.threads,
                    iters=args.iters,
                    fuzz=not args.no_fuzz,
                    package_dir=package_dir,
                )
            except Exception as e:  # noqa: BLE001 - a crash is a verdict
                print(
                    f"kccap-sanitize: hammer crashed under seed {seed}: "
                    f"{type(e).__name__}: {e}",
                    file=sys.stderr,
                )
                return 2
            part = sanitize.partition(found, baseline, project.repo_root)
            sanitize.publish_metrics(st, part)
            runs.append((seed, part, st))
            dyn_live.extend(part.findings)
            dyn_suppressed.extend(part.suppressed)
            dyn_baselined.extend(part.baselined)

    clean = static.clean and not dyn_live
    if args.as_json:
        artifact = {
            "version": 1,
            "clean": clean,
            "static": static.to_json(),
            "dynamic": {
                "seeds": seeds,
                "threads": args.threads,
                "iters": args.iters,
                "runs": [
                    {
                        "seed": seed,
                        "clean": part.clean,
                        "findings": [f.to_json() for f in part.findings],
                        "suppressed": [
                            f.to_json() for f in part.suppressed
                        ],
                        "stats": st,
                    }
                    for seed, part, st in runs
                ],
            },
        }
        print(json.dumps(artifact, indent=2))
    else:
        for f in static.findings:
            print(f.render())
        for f in dyn_live:
            print(f.render())
        classes = runs[0][2]["instrumented_classes"] if runs else 0
        print(
            f"kccap-sanitize: static {len(static.findings)} finding(s); "
            f"dynamic {len(dyn_live)} finding(s), "
            f"{len(dyn_suppressed)} suppressed inline, "
            f"{len(dyn_baselined)} baselined over {len(runs)} seeded "
            f"run(s) x {classes} instrumented class(es), "
            f"seeds={seeds}"
        )
    return 0 if clean else 1


def main() -> None:  # console_scripts entry
    sys.exit(run())


if __name__ == "__main__":
    main()
