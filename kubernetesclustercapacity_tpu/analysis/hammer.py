"""The package-wide sanitize hammer: every threaded class, 16 threads,
fuzzed schedules, seeded and replayable.

Each driver builds fresh instances of one threaded class *while the
sanitizer is installed* (so their locks are wrapped and their guarded
fields — the statically inferred set from
:func:`..rules_locks.lock_model` — are monitored), then hits them from
``threads`` concurrent workers.  One :func:`run` call covers all
fifteen classes under one instrumentation window per seed; findings
flow through the shared suppression/baseline workflow.

The drivers deliberately exercise the *synchronization surface*, not
the numerics: stubs stand in for kernels and oracles, snapshots are
tiny, and every expected control-flow exception (admission sheds,
breaker refusals) is caught inside the op.  What must survive is the
locking — the detector decides whether it did.
"""

from __future__ import annotations

import os
import tempfile
import threading

from kubernetesclustercapacity_tpu.analysis import sanitize
from kubernetesclustercapacity_tpu.analysis.engine import Project
from kubernetesclustercapacity_tpu.analysis.rules_locks import lock_model

__all__ = ["run", "HAMMERED_CLASSES", "instrument_targets"]

#: The fifteen threaded classes the tier-1 gate certifies, as
#: ``(module, class name)`` — every one must also be inferred threaded
#: by the static model (cross-checked in tests/test_sanitize.py).
HAMMERED_CLASSES = (
    ("kubernetesclustercapacity_tpu.devcache", "DeviceCache"),
    ("kubernetesclustercapacity_tpu.service.batching", "MicroBatcher"),
    ("kubernetesclustercapacity_tpu.timeline.history", "CapacityTimeline"),
    ("kubernetesclustercapacity_tpu.audit.log", "AuditLog"),
    ("kubernetesclustercapacity_tpu.audit.shadow", "ShadowSampler"),
    ("kubernetesclustercapacity_tpu.service.plane", "PlanePublisher"),
    ("kubernetesclustercapacity_tpu.service.plane", "PlaneSubscriber"),
    ("kubernetesclustercapacity_tpu.federation.server", "ClusterFeed"),
    ("kubernetesclustercapacity_tpu.service.plane", "AdmissionController"),
    ("kubernetesclustercapacity_tpu.service.tenancy", "FairSlotQueue"),
    ("kubernetesclustercapacity_tpu.resilience", "TokenBucket"),
    ("kubernetesclustercapacity_tpu.resilience", "CircuitBreaker"),
    ("kubernetesclustercapacity_tpu.telemetry.metrics", "MetricsRegistry"),
    ("kubernetesclustercapacity_tpu.telemetry.tracectx", "TailSampler"),
    ("kubernetesclustercapacity_tpu.telemetry.memledger", "DeviceLedger"),
)


def _package_dir() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def instrument_targets(package_dir: str | None = None):
    """``(cls, monitored fields, label)`` for every hammered class —
    the monitored set IS the static model's guarded set, so the two
    provers cannot drift apart."""
    import importlib

    model = lock_model(Project(package_dir or _package_dir()))
    by_name = {}
    for m in model.values():
        by_name.setdefault(m.name, m)
    out = []
    for module, cls_name in HAMMERED_CLASSES:
        cls = getattr(importlib.import_module(module), cls_name)
        m = by_name.get(cls_name)
        if m is None:
            raise RuntimeError(
                f"{cls_name} is hammered but the static lock model does "
                "not infer it threaded — the provers disagree"
            )
        out.append((cls, tuple(sorted(m.guarded)), cls_name))
    return out


# ---------------------------------------------------------------------------
# Per-class drivers.  Each returns (ops, cleanup): ops is a list of
# ``fn(i, t)`` callables the workers round-robin; cleanup tears the
# instances down after the join.


def _drive_device_cache():
    from kubernetesclustercapacity_tpu.devcache import DeviceCache

    cache = DeviceCache(max_entries=4)

    class _Snap:
        pass

    snaps = [_Snap() for _ in range(4)]

    def get(i, t):
        s = snaps[(i + t) % len(snaps)]
        cache.get(s, ("exact", 64 << (i % 2)), lambda: (i, t))

    def stats(i, t):
        cache.stats()

    return [get, get, stats], lambda: None


def _drive_micro_batcher():
    from kubernetesclustercapacity_tpu.service.batching import MicroBatcher

    mb = MicroBatcher(
        lambda key, items: [x * 2 for x in items],
        window_s=0.0005,
        max_batch=8,
    )

    def submit(i, t):
        assert mb.submit(("gen", i % 2), i) == i * 2

    def stats(i, t):
        mb.stats  # property: reads the registry families

    return [submit, submit, submit, stats], lambda: None


def _drive_fold_queue():
    """The generalized cross-spec fold queue (ISSUE 19): the same
    MicroBatcher class, but driven the way the server now drives it —
    keys carrying (generation, semantics, kernel family), weighted
    items, per-member deadlines racing the window budget, tenant tags
    flowing into a fold_hook, and a dispatcher that answers per-member
    slices out of one concatenated launch.  The deadline-bypass path
    and the leader's hook/histogram bookkeeping all run under the
    sanitizer here."""
    from kubernetesclustercapacity_tpu.resilience import Deadline
    from kubernetesclustercapacity_tpu.service.batching import MicroBatcher

    hook_lock = threading.Lock()
    hook_calls = [0]

    def fold_hook(tenants):
        with hook_lock:
            hook_calls[0] += 1
        assert len(tenants) >= 1

    def dispatch(key, items):
        # One folded "launch": every member's answer is its own item
        # scaled — per-member slicing of a shared result, shaped like
        # the server's scatter loop.
        _gen, _sem, _fam = key
        return [(spec, spec * 2) for spec in items]

    mb = MicroBatcher(
        dispatch, window_s=0.0008, max_batch=8, fold_hook=fold_hook
    )
    keys = (
        (("g", 0), "reference", "auto"),
        (("g", 0), "strict", "auto"),
        (("g", 1), "reference", "pallas"),
    )

    def folded(i, t):
        key = keys[(i + t) % len(keys)]
        got = mb.submit(
            key,
            i,
            tenant=f"team-{t % 3}",
            weight=1 + i % 4,
        )
        assert got == (i, i * 2)

    def racing_deadline(i, t):
        # Deadlines straddling the window budget: some members join,
        # some bypass solo — the per-member decision runs under the
        # batch lock and must never double-dispatch.
        key = keys[i % len(keys)]
        got = mb.submit(
            key,
            i,
            deadline=Deadline.after(0.0002 + (i % 5) * 0.0004),
            tenant=f"team-{i % 2}",
        )
        assert got == (i, i * 2)

    def stats(i, t):
        st = mb.stats
        assert st["fold_rate"] >= 0.0
        assert st["mean_folded_specs"] >= 0.0

    return [folded, folded, racing_deadline, stats], lambda: None


def _drive_timeline():
    from kubernetesclustercapacity_tpu.snapshot import synthetic_snapshot
    from kubernetesclustercapacity_tpu.timeline.history import (
        CapacityTimeline,
    )

    tl = CapacityTimeline(depth=8)
    snap = synthetic_snapshot(8, seed=3)
    gen_lock = threading.Lock()
    gen = [0]

    def observe(i, t):
        with gen_lock:
            gen[0] += 1
            g = gen[0]
        tl.observe(snap, g)

    def read(i, t):
        tl.records()
        tl.alerts()
        tl.stats()

    return [observe, read, read], tl.close


def _drive_audit_log(tmpdir):
    from kubernetesclustercapacity_tpu.audit.log import AuditLog
    from kubernetesclustercapacity_tpu.snapshot import synthetic_snapshot

    log = AuditLog(os.path.join(tmpdir, "audit"), checkpoint_every=4)
    snap = synthetic_snapshot(8, seed=3)
    gen_lock = threading.Lock()
    gen = [0]

    def generation(i, t):
        with gen_lock:
            gen[0] += 1
            g = gen[0]
        log.record_generation(snap, g)

    def request(i, t):
        log.record_request(
            op="sweep",
            args={"i": i, "t": t},
            generation=gen[0],
            status="ok",
        )

    def stats(i, t):
        log.stats()
        log.generation_ref(1)

    return [generation, request, request, stats], log.close


def _drive_shadow(tmpdir):
    from kubernetesclustercapacity_tpu.audit.shadow import ShadowSampler

    served = [3, 5]

    sampler = ShadowSampler(
        1.0,
        oracle=lambda snapshot, grid, node_mask: list(served),
        bundle_path=os.path.join(tmpdir, "bundles.jsonl"),
        max_queue=64,
    )

    def submit(i, t):
        sampler.maybe_submit(None, i, None, served, [True, True])

    def stats(i, t):
        sampler.stats()
        sampler.diverged  # property

    def close():
        sampler.drain(timeout_s=10.0)
        sampler.close()

    return [submit, submit, stats], close


def _drive_plane(tmpdir):
    """PlanePublisher + PlaneSubscriber + ClusterFeed in one driver —
    the federation wiring: a real leader fans frames to a subscriber
    staging into a feed, while workers publish and read stats."""
    from kubernetesclustercapacity_tpu.federation.server import ClusterFeed
    from kubernetesclustercapacity_tpu.service.plane import (
        PlanePublisher,
        PlaneSubscriber,
    )
    from kubernetesclustercapacity_tpu.snapshot import synthetic_snapshot

    pub = PlanePublisher(heartbeat_s=0.05)
    feed = ClusterFeed("hammer-cluster")
    sub = PlaneSubscriber(
        pub.address, feed, stale_after_s=5.0, reconnect_base_s=0.01
    )
    snap = synthetic_snapshot(8, seed=3)
    gen_lock = threading.Lock()
    gen = [0]

    def publish(i, t):
        with gen_lock:
            gen[0] += 1
            g = gen[0]
            # Publish order is the plane's contract (the server's
            # coalescer serializes it); the lock models that.
            pub.publish(snap, g)

    def pub_stats(i, t):
        pub.stats()

    def sub_stats(i, t):
        sub.stats()
        sub.stale  # property
        sub.applied_generation  # property
        sub.sync_age_s()

    def feed_view(i, t):
        feed.view()
        feed.last_verified_age_s()
        feed.stream_stats()

    def close():
        sub.stop()
        pub.close()

    return [publish, pub_stats, sub_stats, feed_view], close


def _drive_admission():
    from kubernetesclustercapacity_tpu.resilience import (
        DeadlineExpired,
        OverloadedError,
    )
    from kubernetesclustercapacity_tpu.service.plane import (
        AdmissionController,
    )

    ac = AdmissionController(max_concurrent=4, rps=10000.0)

    def admit(i, t):
        try:
            release = ac.admit("sweep")
        except (OverloadedError, DeadlineExpired):
            return
        try:
            pass
        finally:
            release()

    def price(i, t):
        ac.observe_shadow_price(0.25 * (i % 4), certified=bool(i % 2))
        ac.shadow_price()

    def shed(i, t):
        ac.count_shed("sweep", "draining")

    return [admit, admit, price, shed], lambda: None


def _drive_fair_queue():
    """Adversarial schedules against the weighted-fair admission queue:
    a saturated slot pool, skewed weights, timed-out waiters racing
    grants, and readers — the no-tenant-starves-another class.  Every
    acquire is paired with a release (ValueError on a pairing bug is a
    real finding, not expected control flow)."""
    from kubernetesclustercapacity_tpu.service.tenancy import FairSlotQueue

    weights = {"hot": 8.0, "warm": 2.0, "cold": 0.5}
    fq = FairSlotQueue(4, weight_of=lambda t: weights.get(t, 1.0))
    tenants = ("hot", "hot", "warm", "cold", "other")

    def acquire(i, t):
        tenant = tenants[(i + t) % len(tenants)]
        # Short timeout: under 16 saturating workers many waits expire,
        # exercising the timeout-vs-grant race on purpose.
        if fq.acquire(tenant, timeout=0.002):
            fq.release(tenant)

    def fast(i, t):
        tenant = tenants[(i * 3 + t) % len(tenants)]
        if fq.try_acquire(tenant):
            fq.release(tenant)

    def stats(i, t):
        fq.stats()

    return [acquire, acquire, fast, stats], lambda: None


def _drive_token_bucket():
    from kubernetesclustercapacity_tpu.resilience import TokenBucket

    tb = TokenBucket(1000.0, 64.0)

    def acquire(i, t):
        tb.try_acquire(1.0)

    def avail(i, t):
        tb.available()

    return [acquire, acquire, avail], lambda: None


def _drive_breaker():
    from kubernetesclustercapacity_tpu.resilience import CircuitBreaker

    br = CircuitBreaker(failure_threshold=3, recovery_timeout_s=0.01)

    def ok(i, t):
        if br.allow():
            br.record_success()

    def fail(i, t):
        if br.allow():
            br.record_failure(RuntimeError("hammer"))

    def read(i, t):
        br.state  # property
        br.last_error  # property
        br.snapshot()

    def reset(i, t):
        if i % 7 == 0:
            br.reset()

    return [ok, fail, read, reset], lambda: None


def _drive_registry():
    from kubernetesclustercapacity_tpu.telemetry.metrics import (
        MetricsRegistry,
    )

    reg = MetricsRegistry()

    def counter(i, t):
        reg.counter(
            f"kccap_hammer_c{i % 3}_total", "hammer", ("k",)
        ).labels(k=str(t % 2)).inc()

    def gauge(i, t):
        reg.gauge(f"kccap_hammer_g{i % 2}", "hammer").set(i)

    def collect(i, t):
        reg.collect()
        reg.snapshot()

    return [counter, gauge, collect], lambda: None


def _drive_tail_sampler():
    """The tail-sampling ring under exact-count audit: every span body
    ever recorded must end the run as kept, dropped, or still buffered
    — and kept must equal what actually reached the sink.  Off-by-one
    races in the ring's eviction/flush accounting have nowhere to
    hide."""
    from kubernetesclustercapacity_tpu.telemetry.tracectx import TailSampler

    class _CountingSink:
        def __init__(self):
            self.lock = threading.Lock()
            self.written = 0

        def record(self, **fields):
            with self.lock:
                self.written += 1

    sink = _CountingSink()
    ts = TailSampler(sink, "rate:3", max_traces=8, max_spans_per_trace=4)
    issued = [0]
    issued_lock = threading.Lock()

    def _record(tid):
        ts.record(trace_id=tid, span_id="s", duration_ms=1.0, op="hammer")
        with issued_lock:
            issued[0] += 1

    def own_trace(i, t):
        # The normal request shape: buffer a few spans, decide, finish.
        tid = f"T{t}.{i}"
        _record(tid)
        _record(tid)
        ts.finish(tid, keep=ts.decide("hammer", 0.001, None))

    def hot_trace(i, t):
        # Every thread piles into the same two traces: contends the
        # per-trace span cap and the max_traces eviction path.
        _record(f"hot{i % 2}")

    def finish_hot(i, t):
        ts.finish(f"hot{i % 2}", keep=bool(i % 2))

    def stats(i, t):
        ts.stats()

    def cleanup():
        with ts._lock:
            buffered = sum(len(b) for b in ts._ring.values())
            kept, dropped = ts.kept_spans, ts.dropped_spans
        if kept != sink.written:
            raise AssertionError(
                f"tail-sampler ledger drifted from the sink: "
                f"kept={kept} written={sink.written}"
            )
        if kept + dropped + buffered != issued[0]:
            raise AssertionError(
                "tail-sampler lost or invented spans: "
                f"kept={kept} + dropped={dropped} + buffered={buffered} "
                f"!= issued={issued[0]}"
            )

    return [own_trace, own_trace, hot_trace, finish_hot, stats], cleanup


def _drive_memledger():
    """The device-memory ledger under exact-bytes audit: workers stage
    and retire leaf containers in per-thread slots (mirrored in a
    ledger-independent book) while reconcilers sweep with the mirror as
    the injected live-array view and readers scrape.  The mirror is
    maintained so it always covers the ledger (add-before-register,
    retire-before-remove), so a reconcile mid-race may see suspects but
    never a sustained leak.  After the join the ledger must equal the
    mirror to the byte — accounting that drifts under contention is
    exactly the silent HBM leak the ledger exists to catch."""
    from kubernetesclustercapacity_tpu.telemetry.memledger import (
        DeviceLedger,
    )

    class _Leaf:
        __slots__ = ("nbytes",)

        def __init__(self, nbytes):
            self.nbytes = nbytes

    ledger = DeviceLedger()
    mirror_lock = threading.Lock()
    # (thread, slot) -> (container, nbytes); each thread stages only
    # into its own slots, so the mirror ordering invariant holds.
    mirror: dict = {}
    forms = ("exact", "grouped", "pallas", "fold_fetch")

    def _unstage(key):
        with mirror_lock:
            entry = mirror.get(key)
        if entry is None:
            return
        ledger.retire(entry[0])
        with mirror_lock:
            del mirror[key]

    def stage(i, t):
        key = (t, i % 4)
        _unstage(key)
        leaves = tuple(
            _Leaf(64 * (1 + (i + t + k) % 3)) for k in range(2)
        )
        nbytes = sum(x.nbytes for x in leaves)
        with mirror_lock:
            mirror[key] = (leaves, nbytes)
        ledger.register(leaves, forms[(i + t) % len(forms)])

    def retire(i, t):
        _unstage((t, (i + 1) % 4))

    def reconcile(i, t):
        # Snapshot + reconcile under the mirror lock: a live view that
        # raced a register would mark fresh leaves missing, and id
        # reuse after a free could turn that transient into a phantom
        # "sustained" leak.  Real deployments reconcile against
        # jax.live_arrays() taken inside the call; the hammer's mirror
        # must be at least that coherent.  (Safe: no worker holds the
        # ledger lock while taking the mirror lock.)
        with mirror_lock:
            live = [
                leaf for c, _ in mirror.values() for leaf in c
            ]
            audit = ledger.reconcile(live_arrays=live)
        assert audit["sustained_missing_bytes"] == 0

    def read(i, t):
        ledger.stats()
        ledger.total_bytes()
        ledger.peak_bytes()
        ledger.budget_breached()

    def cleanup():
        with mirror_lock:
            expected = sum(n for _, n in mirror.values())
            count = len(mirror)
        st = ledger.stats()
        if st["total_bytes"] != expected or st["entries"] != count:
            raise AssertionError(
                "memledger drifted from the mirror book: "
                f"total={st['total_bytes']} expected={expected} "
                f"entries={st['entries']} expected_entries={count}"
            )
        if st["registered"] - st["retired"] != count:
            raise AssertionError(
                "memledger lost or invented registrations: "
                f"registered={st['registered']} retired={st['retired']} "
                f"live_entries={count}"
            )
        if ledger.leaking():
            raise AssertionError(
                "memledger reported a sustained leak under a mirror "
                "that always covered the book"
            )

    return [stage, stage, retire, reconcile, read], cleanup


# ---------------------------------------------------------------------------


def _spin(ops, *, threads: int, iters: int) -> list:
    """Round-robin the ops across ``threads`` workers; unexpected
    exceptions are collected and re-raised after the join (a hammer
    that swallows crashes would certify garbage)."""
    errors: list = []
    barrier = threading.Barrier(threads)

    def worker(t: int) -> None:
        try:
            barrier.wait(timeout=30)
            for i in range(iters):
                ops[(t + i) % len(ops)](i, t)
        except Exception as e:  # noqa: BLE001 - surfaced after join
            errors.append(e)

    ts = [
        threading.Thread(target=worker, args=(t,), daemon=True)
        for t in range(threads)
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    return errors


def run(
    *,
    seed: int,
    threads: int = 16,
    iters: int = 40,
    fuzz: bool = True,
    package_dir: str | None = None,
) -> tuple:
    """One full hammer pass: install → drive all fifteen classes
    (the MicroBatcher twice: once as the legacy coalescer, once as the
    generalized fold queue) → report → uninstall.  Returns ``(findings, stats)`` with findings
    relative to the repo root.  Raises if any worker crashed."""
    targets = instrument_targets(package_dir)
    repo_root = os.path.dirname(package_dir or _package_dir())
    sanitize.install(seed=seed, fuzz=fuzz, classes=targets)
    try:
        with tempfile.TemporaryDirectory(prefix="kccap-sanitize-") as tmp:
            drivers = (
                _drive_device_cache(),
                _drive_micro_batcher(),
                _drive_fold_queue(),
                _drive_timeline(),
                _drive_audit_log(tmp),
                _drive_shadow(tmp),
                _drive_plane(tmp),
                _drive_admission(),
                _drive_fair_queue(),
                _drive_token_bucket(),
                _drive_breaker(),
                _drive_registry(),
                _drive_tail_sampler(),
                _drive_memledger(),
            )
            errors: list = []
            try:
                for ops, _cleanup in drivers:
                    errors.extend(_spin(ops, threads=threads, iters=iters))
            finally:
                for _ops, cleanup in drivers:
                    try:
                        cleanup()
                    except Exception as e:  # noqa: BLE001 - keep closing
                        errors.append(e)
        if errors:
            raise RuntimeError(
                f"hammer workers crashed (seed {seed}): "
                + "; ".join(f"{type(e).__name__}: {e}" for e in errors[:5])
            )
        san = sanitize.current()
        found = sanitize.findings_of(san, repo_root)
        st = sanitize.stats_of(san)
        return found, st
    finally:
        sanitize.uninstall()
