"""Analyzer core: findings, suppressions, baseline, and the rule runner.

Design constraints that shaped this module:

* **Never import the analyzed code.**  Every rule works on ASTs and
  source text, so ``kccap-lint`` runs identically with or without a TPU
  backend, and a module with an import-time bug still gets linted.
* **Line-independent baseline identity.**  A finding's baseline key is
  ``(rule, path, symbol)`` — the ``symbol`` is a stable semantic anchor
  (function qname, ``Class.field@method``, metric name) so an unrelated
  edit shifting line numbers does not resurrect baselined findings.
* **Suppression is visible at the offending line.**  ``# kccap:
  lint-ok[rule]`` (trailing on the flagged line, or a standalone
  comment on the line above) admits exactly the named rules —
  ``lint-ok[*]`` admits everything — so every accepted violation is
  greppable next to the code it excuses.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass

__all__ = [
    "Finding",
    "SourceFile",
    "Project",
    "Baseline",
    "Analyzer",
    "AnalysisResult",
    "parse_suppressions",
]

SEVERITIES = ("error", "warning")

#: ``# kccap: lint-ok[rule-a,rule-b]`` (optionally followed by prose).
_SUPPRESS_RE = re.compile(
    r"#\s*kccap:\s*lint-ok\[\s*([A-Za-z0-9_\-*]+(?:\s*,\s*[A-Za-z0-9_\-*]+)*)\s*\]"
)


@dataclass(frozen=True)
class Finding:
    """One analyzer verdict, anchored at ``path:line:col``."""

    rule: str
    severity: str
    path: str  # repo-relative, forward slashes
    line: int
    col: int
    message: str
    symbol: str = ""  # stable anchor used for baseline identity

    def key(self) -> tuple[str, str, str]:
        """Baseline identity — deliberately line-independent."""
        return (self.rule, self.path, self.symbol)

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"{self.severity} [{self.rule}] {self.message}"
        )

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "symbol": self.symbol,
        }


def parse_suppressions(text: str) -> dict[int, set[str]]:
    """Map line number -> rule names admitted on that line.

    A trailing marker admits its own line; a standalone comment line
    admits the line below it (the only line a finding can anchor to).
    """
    out: dict[int, set[str]] = {}
    for lineno, line in enumerate(text.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        out.setdefault(lineno, set()).update(rules)
        if line.lstrip().startswith("#"):
            out.setdefault(lineno + 1, set()).update(rules)
    return out


class SourceFile:
    """One parsed package source: text, AST, and suppression map."""

    def __init__(self, abs_path: str, rel_path: str) -> None:
        self.abs_path = abs_path
        self.rel_path = rel_path.replace(os.sep, "/")
        with open(abs_path, encoding="utf-8") as fh:
            self.text = fh.read()
        self.tree = ast.parse(self.text, filename=rel_path)
        self.suppressions = parse_suppressions(self.text)

    def allows(self, rule: str, line: int) -> bool:
        admitted = self.suppressions.get(line, ())
        return "*" in admitted or rule in admitted


class Project:
    """The analyzed universe: a package directory plus repo context.

    ``package_dir`` is the python package to analyze (every ``*.py``
    under it, ``__pycache__`` pruned); ``repo_root`` (default: the
    package's parent) anchors relative paths and locates the README the
    surface rules check against.
    """

    def __init__(
        self,
        package_dir: str,
        repo_root: str | None = None,
        readme_path: str | None = None,
    ) -> None:
        self.package_dir = os.path.abspath(package_dir)
        if not os.path.isdir(self.package_dir):
            raise FileNotFoundError(f"not a directory: {package_dir}")
        self.repo_root = os.path.abspath(
            repo_root if repo_root else os.path.dirname(self.package_dir)
        )
        self.package_name = os.path.basename(self.package_dir.rstrip(os.sep))
        self.readme_path = readme_path or os.path.join(
            self.repo_root, "README.md"
        )
        self.files: list[SourceFile] = []
        for root, dirs, names in os.walk(self.package_dir):
            dirs[:] = sorted(d for d in dirs if d != "__pycache__")
            for name in sorted(names):
                if not name.endswith(".py"):
                    continue
                abs_path = os.path.join(root, name)
                rel = os.path.relpath(abs_path, self.repo_root)
                self.files.append(SourceFile(abs_path, rel))

    def readme_text(self) -> str:
        if not os.path.exists(self.readme_path):
            return ""
        with open(self.readme_path, encoding="utf-8") as fh:
            return fh.read()

    def file_by_module_tail(self, *tail: str) -> SourceFile | None:
        """The source whose path ends with ``tail`` (e.g. ``("service",
        "server.py")``), or ``None``."""
        suffix = "/".join(tail)
        for f in self.files:
            if f.rel_path.endswith(suffix):
                return f
        return None


class Baseline:
    """The checked-in set of accepted findings plus its history log.

    Shape on disk::

        {
          "version": 1,
          "history": ["<date> <PR>: <what was fixed/accepted and why>"],
          "findings": [{"rule": ..., "path": ..., "symbol": ...}, ...]
        }

    Matching is by :meth:`Finding.key` — line numbers are deliberately
    absent so the baseline survives unrelated edits.
    """

    def __init__(
        self,
        entries: set[tuple[str, str, str]] | None = None,
        history: list[str] | None = None,
    ) -> None:
        self.entries = set(entries or ())
        self.history = list(history or ())

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls()
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
        if not isinstance(data, dict) or "findings" not in data:
            raise ValueError(f"malformed baseline file: {path}")
        entries = {
            (e["rule"], e["path"], e.get("symbol", ""))
            for e in data["findings"]
        }
        return cls(entries, data.get("history", []))

    @classmethod
    def from_findings(
        cls, findings: list[Finding], history: list[str] | None = None
    ) -> "Baseline":
        return cls({f.key() for f in findings}, history)

    def save(self, path: str) -> None:
        data = {
            "version": 1,
            "history": self.history,
            "findings": [
                {"rule": r, "path": p, "symbol": s}
                for (r, p, s) in sorted(self.entries)
            ],
        }
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(data, fh, indent=2, sort_keys=False)
            fh.write("\n")

    def matches(self, finding: Finding) -> bool:
        return finding.key() in self.entries


@dataclass
class AnalysisResult:
    """Everything one analyzer run produced, pre-partitioned."""

    findings: list[Finding]  # live (not suppressed, not baselined)
    suppressed: list[Finding]  # admitted by an inline lint-ok marker
    baselined: list[Finding]  # admitted by the checked-in baseline

    @property
    def clean(self) -> bool:
        return not self.findings

    def to_json(self) -> dict:
        by_rule: dict[str, int] = {}
        for f in self.findings:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        return {
            "version": 1,
            "clean": self.clean,
            "counts": {
                "findings": len(self.findings),
                "suppressed": len(self.suppressed),
                "baselined": len(self.baselined),
                "by_rule": dict(sorted(by_rule.items())),
            },
            "findings": [f.to_json() for f in self.findings],
            "suppressed": [f.to_json() for f in self.suppressed],
        }


def _default_rules():
    # Local import: the rule modules import engine types, so the
    # registry lives behind a function to avoid a cycle at import time.
    from kubernetesclustercapacity_tpu.analysis import (
        rules_hygiene,
        rules_jit,
        rules_lockorder,
        rules_locks,
        rules_surface,
    )

    return {
        "jit-purity": rules_jit.check,
        "lock-discipline": rules_locks.check,
        "lock-order": rules_lockorder.check,
        "surface": rules_surface.check,
        "hygiene": rules_hygiene.check,
    }


class Analyzer:
    """Run rule families over a :class:`Project` and partition findings.

    ``rules`` restricts to a subset of family names (``jit-purity``,
    ``lock-discipline``, ``surface``, ``hygiene``); the surface family
    emits per-walk rule ids (``surface-metric``, ``surface-env``, ...)
    which suppressions and baselines key on.
    """

    def __init__(
        self,
        project: Project,
        rules: tuple[str, ...] | None = None,
        baseline: Baseline | None = None,
    ) -> None:
        registry = _default_rules()
        unknown = set(rules or ()) - set(registry)
        if unknown:
            raise ValueError(
                f"unknown rule families {sorted(unknown)}; "
                f"available: {sorted(registry)}"
            )
        self.project = project
        self.rule_fns = {
            name: fn
            for name, fn in registry.items()
            if rules is None or name in rules
        }
        self.baseline = baseline or Baseline()

    def run(self) -> AnalysisResult:
        raw: list[Finding] = []
        for _, fn in sorted(self.rule_fns.items()):
            raw.extend(fn(self.project))
        raw.sort(key=lambda f: (f.path, f.line, f.col, f.rule, f.symbol))

        by_path = {f.rel_path: f for f in self.project.files}
        live: list[Finding] = []
        suppressed: list[Finding] = []
        baselined: list[Finding] = []
        for f in raw:
            src = by_path.get(f.path)
            if src is not None and src.allows(f.rule, f.line):
                suppressed.append(f)
            elif self.baseline.matches(f):
                baselined.append(f)
            else:
                live.append(f)
        return AnalysisResult(live, suppressed, baselined)
