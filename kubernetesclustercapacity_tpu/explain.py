"""Capacity explainability: WHY the sweep stopped at N replicas.

The reference's entire diagnostic story is four ``fmt.Printf`` percentages
that never influence the fit (``ClusterCapacity.go:113-117``, SURVEY.md §5).
This module answers the question an operator actually asks: for every
(scenario, node), which constraint is *binding* — cpu, memory, pod slots,
or node health — how much headroom is left after the fit, and what is the
smallest additional allocatable of each resource that would yield one more
replica anywhere in the cluster.

Two layers, split by where the math belongs:

* a **vectorized JAX pass** (:func:`explain_per_node` / :func:`explain_grid`)
  alongside :mod:`.ops.fit` — the same bit-faithful arithmetic as
  ``fit_per_node`` (uint64 CPU views, wrap-around memory, truncating
  division, the Q1 conditional pod-cap overwrite) extended to return the
  per-constraint fit components and a binding-attribution code per node.
  Pure array math: no registry call, no host object, jit/vmap-compatible.
* **host-side analysis** (:class:`ExplainResult`) — binding histograms,
  saturation distributions, and the marginal ("+1 replica") analysis,
  numpy/Python over the kernel's outputs.  The marginal candidates come
  from the monotone closed form and every reported delta is *verified*
  against the sequential bug-compatible evaluator
  (:func:`..oracle.fit_arrays_python`), so reference-mode non-monotonicity
  (the Q1 overwrite can DECREASE a fit when capacity grows) can never
  produce a wrong recommendation — a candidate the full semantics rejects
  is skipped, never reported.

Attribution rule (deterministic, shared with the brute-force oracle in
``tests/test_explain.py``):

* ``unhealthy`` — the node's ``healthy`` flag is false (strict: masked out
  of the fit; reference: the phantom zero-row the packer produced);
* ``masked``    — an explicit ``node_mask`` zeroed the node (constraint
  infeasibility — an extension, like the kernel's own mask);
* otherwise the FIRST minimum, in order ``cpu ≺ memory ≺ pods``, of the
  values the mode's min actually compares: strict compares
  ``(cpu_fit, mem_fit, slots)``; reference has no pod term in the min —
  its ``pods`` attribution is the Q1 overwrite having fired
  (``min(cpu_fit, mem_fit) >= allocatable_pods``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from kubernetesclustercapacity_tpu.oracle import fit_arrays_python
from kubernetesclustercapacity_tpu.ops.fit import _trunc_div
from kubernetesclustercapacity_tpu.scenario import ScenarioGrid
from kubernetesclustercapacity_tpu.snapshot import (
    ClusterSnapshot,
    grouped_for_dispatch,
)

__all__ = [
    "BINDING_NAMES",
    "BINDING_CPU",
    "BINDING_MEMORY",
    "BINDING_PODS",
    "BINDING_UNHEALTHY",
    "BINDING_MASKED",
    "ExplainResult",
    "binding_shift",
    "explain_per_node",
    "explain_grid",
    "explain_snapshot",
    "sweep_explain_snapshot",
]

# Attribution codes, in tie-break order (cpu ≺ memory ≺ pods); health and
# mask overrides sit above the resource codes.
BINDING_CPU = 0
BINDING_MEMORY = 1
BINDING_PODS = 2
BINDING_UNHEALTHY = 3
BINDING_MASKED = 4
BINDING_NAMES = ("cpu", "memory", "pods", "unhealthy", "masked")

_U64 = 1 << 64
# Deltas beyond this are not actionable advice ("add 4 exabytes") and
# would push the int64 carrier into wrap territory — treated as "this
# resource cannot buy +1 here".
_MAX_SANE_DELTA = 1 << 62


@partial(jax.jit, static_argnames=("mode",))
def explain_per_node(
    alloc_cpu: jnp.ndarray,
    alloc_mem: jnp.ndarray,
    alloc_pods: jnp.ndarray,
    used_cpu: jnp.ndarray,
    used_mem: jnp.ndarray,
    pods_count: jnp.ndarray,
    healthy: jnp.ndarray,
    cpu_req,
    mem_req,
    *,
    mode: str = "reference",
    node_mask: jnp.ndarray | None = None,
):
    """Fit + binding attribution for ONE scenario.

    Returns ``(fit, code, cpu_fit, mem_fit, slots)`` — all ``[N]``; ``fit``
    is bit-identical to :func:`..ops.fit.fit_per_node` (pinned by
    ``tests/test_explain.py``), ``code`` the attribution per the module
    rule, ``cpu_fit``/``mem_fit`` the per-resource quotients on their
    int64 carriers, and ``slots`` the pod term the mode compares
    (``alloc_pods - pods_count``, clamped at 0 in strict mode only).
    """
    alloc_cpu = jnp.asarray(alloc_cpu, jnp.int64)
    alloc_mem = jnp.asarray(alloc_mem, jnp.int64)
    alloc_pods = jnp.asarray(alloc_pods, jnp.int64)
    used_cpu = jnp.asarray(used_cpu, jnp.int64)
    used_mem = jnp.asarray(used_mem, jnp.int64)
    pods_count = jnp.asarray(pods_count, jnp.int64)
    cpu_req = jnp.asarray(cpu_req, jnp.int64)
    mem_req = jnp.asarray(mem_req, jnp.int64)
    healthy_b = jnp.asarray(healthy, jnp.bool_)

    # Identical prologue to fit_per_node: uint64 CPU compare/divide on the
    # raw bit patterns, int64 wrap-around memory with truncating division.
    alloc_cpu_u = alloc_cpu.astype(jnp.uint64)
    used_cpu_u = used_cpu.astype(jnp.uint64)
    cpu_req_u = jnp.maximum(cpu_req.astype(jnp.uint64), jnp.uint64(1))
    cpu_fit = jnp.where(
        alloc_cpu_u <= used_cpu_u,
        jnp.uint64(0),
        (alloc_cpu_u - used_cpu_u) // cpu_req_u,
    ).astype(jnp.int64)
    mem_head = alloc_mem - used_mem
    mem_fit = jnp.where(
        alloc_mem <= used_mem,
        jnp.int64(0),
        _trunc_div(mem_head, jnp.where(mem_req == 0, jnp.int64(1), mem_req)),
    )
    fit_pre = jnp.minimum(cpu_fit, mem_fit)

    if mode == "reference":
        slots = alloc_pods - pods_count  # unclamped: Q1's replacement value
        q1 = fit_pre >= alloc_pods
        fit = jnp.where(q1, slots, fit_pre)
        code = jnp.where(
            q1,
            jnp.int32(BINDING_PODS),
            jnp.where(
                cpu_fit <= mem_fit,
                jnp.int32(BINDING_CPU),
                jnp.int32(BINDING_MEMORY),
            ),
        )
    elif mode == "strict":
        slots = jnp.maximum(alloc_pods - pods_count, jnp.int64(0))
        fit = jnp.maximum(jnp.minimum(fit_pre, slots), jnp.int64(0))
        fit = jnp.where(healthy_b, fit, jnp.int64(0))
        code = jnp.where(
            (cpu_fit <= mem_fit) & (cpu_fit <= slots),
            jnp.int32(BINDING_CPU),
            jnp.where(
                mem_fit <= slots,
                jnp.int32(BINDING_MEMORY),
                jnp.int32(BINDING_PODS),
            ),
        )
    else:
        raise ValueError(f"unknown mode {mode!r}")
    # Health override: in strict mode the node contributes nothing BECAUSE
    # it is unhealthy; in reference mode the phantom zero-row exists
    # because getHealthyNodes skipped it — either way, "unhealthy" is the
    # answer an operator needs, not "cpu is 0".
    code = jnp.where(healthy_b, code, jnp.int32(BINDING_UNHEALTHY))
    if node_mask is not None:
        mask_b = jnp.asarray(node_mask, jnp.bool_)
        fit = jnp.where(mask_b, fit, jnp.int64(0))
        code = jnp.where(mask_b, code, jnp.int32(BINDING_MASKED))
    return fit, code, cpu_fit, mem_fit, slots


@partial(jax.jit, static_argnames=("mode",))
def explain_grid(
    alloc_cpu,
    alloc_mem,
    alloc_pods,
    used_cpu,
    used_mem,
    pods_count,
    healthy,
    cpu_reqs,
    mem_reqs,
    *,
    mode: str = "reference",
    node_mask=None,
):
    """S-scenario vectorized attribution: each output is ``[S, N]``.

    The scenario axis is a ``vmap`` over the request vectors — one
    compiled program explains a whole sweep, the same way ``sweep_grid``
    evaluates one.
    """
    per_scenario = jax.vmap(
        lambda c, m: explain_per_node(
            alloc_cpu,
            alloc_mem,
            alloc_pods,
            used_cpu,
            used_mem,
            pods_count,
            healthy,
            c,
            m,
            mode=mode,
            node_mask=node_mask,
        )
    )
    return per_scenario(
        jnp.asarray(cpu_reqs, jnp.int64), jnp.asarray(mem_reqs, jnp.int64)
    )


@dataclass
class ExplainResult:
    """Host-side view of an explained sweep (numpy arrays throughout).

    ``fits``/``binding``/``cpu_fit``/``mem_fit``/``slots`` are ``[S, N]``;
    ``totals`` is ``[S]``.  The snapshot rides along for the host-side
    analyses (marginals need the raw allocatable/used columns).
    """

    snapshot: ClusterSnapshot
    mode: str
    cpu_request_milli: np.ndarray  # [S] int64 carriers
    mem_request_bytes: np.ndarray  # [S]
    replicas: np.ndarray  # [S]
    fits: np.ndarray  # [S, N]
    binding: np.ndarray  # [S, N] int32 codes
    cpu_fit: np.ndarray  # [S, N]
    mem_fit: np.ndarray  # [S, N]
    slots: np.ndarray  # [S, N]
    node_mask: np.ndarray | None = field(default=None)

    @property
    def totals(self) -> np.ndarray:
        return self.fits.sum(axis=1)

    @property
    def size(self) -> int:
        return int(self.fits.shape[0])

    def binding_names(self, s: int = 0) -> list[str]:
        """Per-node attribution strings for scenario ``s``."""
        return [BINDING_NAMES[int(c)] for c in self.binding[s]]

    def binding_counts(self, s: int = 0) -> dict[str, int]:
        """``{constraint: node count}`` for scenario ``s`` (zero-count
        constraints included, so the dict shape is stable)."""
        codes, counts = np.unique(self.binding[s], return_counts=True)
        out = {name: 0 for name in BINDING_NAMES}
        for c, n in zip(codes, counts):
            out[BINDING_NAMES[int(c)]] = int(n)
        return out

    # -- headroom / saturation -------------------------------------------
    def headroom(self, s: int = 0) -> dict[str, np.ndarray]:
        """Per-node residual headroom AFTER placing scenario ``s``'s fit.

        ``cpu_milli``/``mem_bytes`` are ``head - fit * request`` (what is
        left once the reported replicas land); ``pod_slots`` the remaining
        schedulable pod slots.  Python-int arithmetic (object arrays are
        avoided by clamping to the sane domain): wrapped/degenerate rows
        report 0 residual rather than garbage.
        """
        snap = self.snapshot
        fit = self.fits[s]
        cr = int(self.cpu_request_milli[s]) % _U64
        mr = int(self.mem_request_bytes[s])
        n = snap.n_nodes
        cpu_res = np.zeros(n, dtype=np.int64)
        mem_res = np.zeros(n, dtype=np.int64)
        pod_res = np.zeros(n, dtype=np.int64)
        for i in range(n):
            f = max(int(fit[i]), 0)
            ch = (int(snap.alloc_cpu_milli[i]) % _U64) - (
                int(snap.used_cpu_req_milli[i]) % _U64
            )
            mh = int(snap.alloc_mem_bytes[i]) - int(
                snap.used_mem_req_bytes[i]
            )
            cpu_res[i] = max(min(ch - f * cr, np.iinfo(np.int64).max), 0)
            mem_res[i] = max(min(mh - f * mr, np.iinfo(np.int64).max), 0)
            pod_res[i] = max(
                int(snap.alloc_pods[i]) - int(snap.pods_count[i]) - f, 0
            )
        return {
            "cpu_milli": cpu_res,
            "mem_bytes": mem_res,
            "pod_slots": pod_res,
        }

    def saturation(self, s: int = 0) -> dict:
        """Cluster saturation summary for scenario ``s``: the binding
        histogram, zero-fit node count, and per-resource utilization
        quantiles over healthy nodes (display-grade floats — the fit
        itself never consumes them, exactly like the reference's
        percentages)."""
        snap = self.snapshot
        out = {
            "binding_counts": self.binding_counts(s),
            "zero_fit_nodes": int((self.fits[s] <= 0).sum()),
            "nodes": snap.n_nodes,
        }
        healthy = np.asarray(snap.healthy, dtype=bool)
        for name, used, alloc in (
            ("cpu_utilization", snap.used_cpu_req_milli, snap.alloc_cpu_milli),
            ("mem_utilization", snap.used_mem_req_bytes, snap.alloc_mem_bytes),
            ("pod_utilization", snap.pods_count, snap.alloc_pods),
        ):
            a = np.asarray(alloc, dtype=np.float64)
            u = np.asarray(used, dtype=np.float64)
            ok = healthy & (a > 0)
            if not ok.any():
                out[name] = None
                continue
            util = u[ok] / a[ok]
            out[name] = {
                "p50": round(float(np.percentile(util, 50)), 4),
                "p90": round(float(np.percentile(util, 90)), 4),
                "max": round(float(util.max()), 4),
                "saturated_nodes": int((util >= 1.0).sum()),
            }
        return out

    # -- marginal analysis -----------------------------------------------
    def marginal(
        self, s: int = 0, *, verify_limit: int | None = 32
    ) -> dict[str, dict | None]:
        """Smallest additional allocatable of each resource buying +1.

        For each resource R in (cpu, memory, pods): the minimal increment
        to ONE node's allocatable R that raises the cluster total by at
        least one replica, holding everything else fixed.  Candidates
        come from the monotone closed form (the exact increment that
        lifts that node's R-bound to ``fit+1``) and are accepted only
        after the full mode semantics — Q1 overwrite included — confirm
        the +1 by re-evaluating the node
        (:func:`..oracle.fit_arrays_python`); candidates the bug-
        compatible evaluator rejects are skipped.  ``verify_limit``
        bounds how many candidates are re-evaluated per resource
        (ascending delta; ``None`` = all).

        Returns ``{resource: {"delta": int, "node": str, "unit": str}}``
        with ``None`` for a resource no single-node increment can buy +1
        through.  Units: millicores, bytes, pod slots.
        """
        snap = self.snapshot
        mode = self.mode
        fit = self.fits[s]
        cpu_fit = self.cpu_fit[s]
        mem_fit = self.mem_fit[s]
        code = self.binding[s]
        cr_u = int(self.cpu_request_milli[s]) % _U64
        mr = int(self.mem_request_bytes[s])
        healthy = np.asarray(snap.healthy, dtype=bool)
        mask = (
            np.ones(snap.n_nodes, dtype=bool)
            if self.node_mask is None
            else np.asarray(self.node_mask, dtype=bool)
        )
        out: dict[str, dict | None] = {}
        for resource, unit in (
            ("cpu", "milli"),
            ("memory", "bytes"),
            ("pods", "slots"),
        ):
            candidates: list[tuple[int, int]] = []  # (delta, node index)
            for i in range(snap.n_nodes):
                if not healthy[i] or not mask[i]:
                    continue  # capacity cannot fix health or constraints
                if code[i] in (BINDING_UNHEALTHY, BINDING_MASKED):
                    continue
                d = self._candidate_delta(
                    resource, i, int(fit[i]) + 1,
                    int(cpu_fit[i]), int(mem_fit[i]), cr_u, mr, mode,
                )
                if d is not None and 0 < d <= _MAX_SANE_DELTA:
                    candidates.append((d, i))
            candidates.sort()
            chosen: dict | None = None
            limit = len(candidates) if verify_limit is None else verify_limit
            for d, i in candidates[:limit]:
                if self._verify_plus_one(resource, i, d, s):
                    chosen = {
                        "delta": int(d),
                        "node": snap.names[i],
                        "node_index": int(i),
                        "unit": unit,
                    }
                    break
            out[resource] = chosen
        return out

    def _candidate_delta(
        self, resource, i, target, cpu_fit_i, mem_fit_i, cr_u, mr, mode
    ) -> int | None:
        """Closed-form minimal increment lifting node ``i``'s R-bound to
        ``target`` replicas — the MONOTONE model's answer, which
        :meth:`_verify_plus_one` then checks against the full semantics.
        Python-int arithmetic throughout (no int64 overflow)."""
        snap = self.snapshot
        ap = int(snap.alloc_pods[i])
        pc = int(snap.pods_count[i])
        if resource == "cpu":
            if mem_fit_i < target:  # memory binds below target regardless
                return None
            head = (int(snap.alloc_cpu_milli[i]) % _U64) - (
                int(snap.used_cpu_req_milli[i]) % _U64
            )
            return target * cr_u - head
        if resource == "memory":
            if cpu_fit_i < target:
                return None
            head = int(snap.alloc_mem_bytes[i]) - int(
                snap.used_mem_req_bytes[i]
            )
            return target * mr - head
        # pods: strict compares remaining slots; reference only consults
        # alloc_pods through the Q1 overwrite, where raising it by 1 adds
        # one replica iff min(cpu_fit, mem_fit) still clears the new cap.
        if min(cpu_fit_i, mem_fit_i) < target:
            return None
        if mode == "strict":
            return target - max(ap - pc, 0)
        # Reference: the minimal useful increment is always 1 slot — the
        # overwrite writes ``alloc_pods - pods_count``, so +1 allocatable
        # is +1 replica exactly when the overwrite still fires at the new
        # cap (min(cpu_fit, mem_fit) >= ap + 1, checked above and then
        # confirmed by verification).
        return 1

    def _verify_plus_one(self, resource, i, delta, s) -> bool:
        """Re-evaluate node ``i`` with ``alloc_R + delta`` under the FULL
        mode semantics; True iff its fit strictly increases."""
        snap = self.snapshot
        ac = int(snap.alloc_cpu_milli[i])
        am = int(snap.alloc_mem_bytes[i])
        ap = int(snap.alloc_pods[i])
        if resource == "cpu":
            ac = ((ac % _U64) + delta) % _U64
            if ac >= 1 << 63:
                ac -= _U64  # back to the int64 carrier
        elif resource == "memory":
            am += delta
            if not (-(1 << 63) <= am < 1 << 63):
                return False
        else:
            ap += delta
        before = int(self.fits[s][i])
        after = fit_arrays_python(
            [ac], [am], [ap],
            [int(snap.used_cpu_req_milli[i])],
            [int(snap.used_mem_req_bytes[i])],
            [int(snap.pods_count[i])],
            int(self.cpu_request_milli[s]),
            int(self.mem_request_bytes[s]),
            mode=self.mode,
            healthy=[bool(snap.healthy[i])],
        )[0]
        return after > before


def binding_shift(
    old_counts: dict[str, int], new_counts: dict[str, int]
) -> dict[str, int]:
    """How a binding histogram MOVED between two explanations.

    ``{constraint: node-count delta}`` with zero-delta constraints
    omitted — the timeline's drift-attribution vocabulary ("binding
    constraint shifted memory→pods on 12 nodes" is ``{"memory": -12,
    "pods": +12}``).  Lives here because this module owns the binding
    taxonomy; the inputs are :meth:`ExplainResult.binding_counts` dicts
    from any two generations.
    """
    return {
        name: new_counts.get(name, 0) - old_counts.get(name, 0)
        for name in BINDING_NAMES
        if new_counts.get(name, 0) != old_counts.get(name, 0)
    }


def explain_snapshot(
    snapshot: ClusterSnapshot,
    grid: ScenarioGrid,
    *,
    mode: str | None = None,
    node_mask=None,
) -> ExplainResult:
    """Explain a whole sweep: ``ClusterSnapshot`` × ``ScenarioGrid`` →
    :class:`ExplainResult` (numpy).  ``mode`` defaults to the snapshot's
    own packing semantics — the same rule the service applies.

    Degenerate fleets run the attribution kernel over node-shape GROUPS
    (:meth:`..snapshot.ClusterSnapshot.grouped`) and expand every
    ``[S, G]`` output back to ``[S, N]`` through the group→node index
    map — identical rows get identical attribution, so the expansion is
    bit-exact and every report stays node-granular.  ``node_mask``
    re-applies per node after expansion (the same last-wins override the
    per-node kernel gives it).  ``KCCAP_GROUPING=0`` restores the
    per-node kernel exactly."""
    mode = mode or snapshot.semantics
    grid.validate()
    grouped = grouped_for_dispatch(snapshot)
    if grouped is not None:
        fits_g, code_g, cpu_fit_g, mem_fit_g, slots_g = explain_grid(
            grouped.alloc_cpu_milli,
            grouped.alloc_mem_bytes,
            grouped.alloc_pods,
            grouped.used_cpu_req_milli,
            grouped.used_mem_req_bytes,
            grouped.pods_count,
            grouped.healthy,
            grid.cpu_request_milli,
            grid.mem_request_bytes,
            mode=mode,
            # No mask inside the kernel: the mask is per NODE, so it is
            # re-applied after the group→node expansion below.
        )
        fits = grouped.expand(np.asarray(fits_g))
        code = grouped.expand(np.asarray(code_g))
        cpu_fit = grouped.expand(np.asarray(cpu_fit_g))
        mem_fit = grouped.expand(np.asarray(mem_fit_g))
        slots = grouped.expand(np.asarray(slots_g))
        if node_mask is not None:
            mask_row = np.asarray(node_mask, dtype=bool)[None, :]
            fits = np.where(mask_row, fits, 0)
            code = np.where(
                mask_row, code, np.int32(BINDING_MASKED)
            ).astype(code.dtype)
    else:
        fits, code, cpu_fit, mem_fit, slots = explain_grid(
            snapshot.alloc_cpu_milli,
            snapshot.alloc_mem_bytes,
            snapshot.alloc_pods,
            snapshot.used_cpu_req_milli,
            snapshot.used_mem_req_bytes,
            snapshot.pods_count,
            snapshot.healthy,
            grid.cpu_request_milli,
            grid.mem_request_bytes,
            mode=mode,
            node_mask=node_mask,
        )
    return ExplainResult(
        snapshot=snapshot,
        mode=mode,
        cpu_request_milli=np.asarray(grid.cpu_request_milli),
        mem_request_bytes=np.asarray(grid.mem_request_bytes),
        replicas=np.asarray(grid.replicas),
        fits=np.asarray(fits),
        binding=np.asarray(code),
        cpu_fit=np.asarray(cpu_fit),
        mem_fit=np.asarray(mem_fit),
        slots=np.asarray(slots),
        node_mask=(
            None if node_mask is None else np.asarray(node_mask, dtype=bool)
        ),
    )

def sweep_explain_snapshot(
    snapshot: ClusterSnapshot,
    grid: ScenarioGrid,
    *,
    mode: str | None = None,
    node_mask=None,
):
    """Fused sweep+explain dispatch: ONE device launch answering both
    "how many fit" and "what binds" for every scenario.

    The super-kernel (:func:`..ops.fit.sweep_explain_grid` /
    ``sweep_explain_grouped``) computes the sweep totals on-device from
    the attribution kernel's fits — which are pinned bit-identical to
    ``fit_per_node``'s, so the totals are bit-exact against a solo
    :func:`..ops.fit.sweep_snapshot` and the per-node outputs bit-exact
    against :func:`explain_snapshot`, in both modes, grouped or not.
    Rides the device cache's bucket-padded node staging when enabled
    (padded rows contribute zero in both modes, exactly as in the
    bucketed sweep; no scenario-axis padding — the ``[S, N]``
    attribution output makes pad probes pure waste).  The grouped route
    folds ``node_mask`` into the per-group effective counts for the
    on-device totals (a masked node's fit is zero in every mode) and
    re-applies it per node after expansion, the same contract as
    :func:`explain_snapshot`.

    Returns ``(totals[S], schedulable[S], ExplainResult, kernel_name)``
    — all numpy; ``kernel_name`` is the honest compilewatch family
    (there is no Pallas route: the attribution needs int64 quotients).
    """
    import time as _time

    from kubernetesclustercapacity_tpu import devcache as _devcache
    from kubernetesclustercapacity_tpu.ops.fit import (
        sweep_explain_grid,
        sweep_explain_grouped,
    )
    from kubernetesclustercapacity_tpu.telemetry import phases as _phases
    from kubernetesclustercapacity_tpu.telemetry.compilewatch import (
        observe_dispatch,
    )
    from kubernetesclustercapacity_tpu.telemetry.metrics import (
        enabled as _telemetry_enabled,
    )

    mode = mode or snapshot.semantics
    grid.validate()
    clk = _phases.current()
    n = snapshot.n_nodes
    grouped = grouped_for_dispatch(snapshot)
    if grouped is not None:
        g = grouped.n_groups
        counts = grouped.effective_counts(node_mask)
        if _devcache.enabled():
            staged = _devcache.CACHE.grouped_arrays(grouped)
            arrays = staged[:7]
            bucket = int(arrays[0].shape[0])
            if node_mask is None:
                counts_p = staged[7]
            else:
                counts_p = (
                    np.pad(counts, (0, bucket - g)) if bucket > g else counts
                )
            label = f"xla_int64_sweep_explain_grouped@g{bucket}"
        else:
            arrays = (
                grouped.alloc_cpu_milli, grouped.alloc_mem_bytes,
                grouped.alloc_pods, grouped.used_cpu_req_milli,
                grouped.used_mem_req_bytes, grouped.pods_count,
                grouped.healthy,
            )
            counts_p = counts
            label = "xla_int64_sweep_explain_grouped"
        t0 = _time.perf_counter()
        out = sweep_explain_grouped(
            *arrays, counts_p,
            grid.cpu_request_milli, grid.mem_request_bytes, grid.replicas,
            mode=mode,
        )
        kernel = "xla_int64_sweep_explain_grouped"
        cols = g
    else:
        if _devcache.enabled():
            arrays = _devcache.CACHE.exact_arrays(snapshot)
            bucket = int(arrays[0].shape[0])
            mask = node_mask
            if mask is not None:
                mask = np.asarray(mask, dtype=bool)
                if bucket > n:
                    mask = np.pad(mask, (0, bucket - n))
            label = f"xla_int64_sweep_explain@n{bucket}"
        else:
            arrays = (
                snapshot.alloc_cpu_milli, snapshot.alloc_mem_bytes,
                snapshot.alloc_pods, snapshot.used_cpu_req_milli,
                snapshot.used_mem_req_bytes, snapshot.pods_count,
                snapshot.healthy,
            )
            mask = node_mask
            label = "xla_int64_sweep_explain"
        t0 = _time.perf_counter()
        out = sweep_explain_grid(
            *arrays,
            grid.cpu_request_milli, grid.mem_request_bytes, grid.replicas,
            mode=mode, node_mask=mask,
        )
        kernel = "xla_int64_sweep_explain"
        cols = n
    t_launch = _time.perf_counter()
    totals = np.asarray(out[0])
    schedulable = np.asarray(out[1])
    per_node = tuple(np.asarray(o)[:, :cols] for o in out[2:])
    t_done = _time.perf_counter()
    kind = None
    if _telemetry_enabled():
        kind = observe_dispatch(label, t_done - t0)
    if clk:
        if kind == "compile":
            clk.record("compile", t_done - t0)
        else:
            clk.record("device_exec", t_launch - t0)
            clk.record("fetch", t_done - t_launch)
    fits, code, cpu_fit, mem_fit, slots = per_node
    if grouped is not None:
        fits = grouped.expand(fits)
        code = grouped.expand(code)
        cpu_fit = grouped.expand(cpu_fit)
        mem_fit = grouped.expand(mem_fit)
        slots = grouped.expand(slots)
        if node_mask is not None:
            mask_row = np.asarray(node_mask, dtype=bool)[None, :]
            fits = np.where(mask_row, fits, 0)
            code = np.where(
                mask_row, code, np.int32(BINDING_MASKED)
            ).astype(code.dtype)
    result = ExplainResult(
        snapshot=snapshot,
        mode=mode,
        cpu_request_milli=np.asarray(grid.cpu_request_milli),
        mem_request_bytes=np.asarray(grid.mem_request_bytes),
        replicas=np.asarray(grid.replicas),
        fits=fits,
        binding=code,
        cpu_fit=cpu_fit,
        mem_fit=mem_fit,
        slots=slots,
        node_mask=(
            None if node_mask is None else np.asarray(node_mask, dtype=bool)
        ),
    )
    return totals, schedulable, result, kernel
