"""Device-memory ledger: HBM accounting that cannot leak silently.

The devcache/donation/fold machinery (PR 4, PR 19) holds device buffers
whose total size was, until now, unknown and unaudited: staged snapshot
tuples (exact / grouped / pallas / gspmd forms), donated replacement
columns, and the async fold path's in-flight ``_FoldedFetch`` device
futures.  This module is the single book those sites write:

* **register/retire by identity** — every staging site registers the
  container it stores (a tuple of device arrays) with its form label;
  retirement happens at the exact point the container leaves the cache
  (LRU eviction, ``invalidate``, ``stage_replace``'s pop, fold
  materialization).  The ledger holds NO strong references — devcache's
  donation guard (``sys.getrefcount(prior) <= 3``) and JAX's buffer
  lifetimes must be unaffected by being observed — so entries are keyed
  on container id with per-leaf ``(id, nbytes)`` pairs captured at
  registration.
* **gauges** — ``kccap_device_bytes{form}`` (live bytes per form) and
  ``kccap_device_peak_bytes`` (high-watermark), both callback gauges so
  a scrape always reads the current book.
* **reconciliation** — :meth:`DeviceLedger.reconcile` checks every
  tracked leaf against ``jax.live_arrays()`` identity.  A tracked leaf
  that is gone from the backend's own accounting means a site freed
  memory without telling the book — and a buffer the book believes
  live that is not, is exactly how an HBM leak hides.  A discrepancy
  must be SUSTAINED (same leaf missing on two consecutive reconciles)
  before it trips the leak :class:`~..timeline.alerts.WatchAlert`,
  which feeds ``/healthz`` and the doctor "device memory" line.
* **budget** — ``-device-budget-bytes`` arms :meth:`set_budget`; live
  bytes above it flip ``budget_breached`` (a signal, not an admission
  gate — the operator chooses the response).

Hot-path rule: when telemetry is off (``KCCAP_TELEMETRY=0``) or the
dedicated hatch is thrown (``KCCAP_MEMLEDGER=0``), :func:`enabled` is
False, every hook site skips the ledger entirely, and this module makes
zero registry calls — pinned by test.
"""

from __future__ import annotations

import os
import threading

from kubernetesclustercapacity_tpu.timeline.alerts import WatchAlert

__all__ = [
    "DeviceLedger",
    "LEDGER",
    "enabled",
    "register",
    "retire",
    "device_memory_status",
]


def enabled() -> bool:
    """Ledger armed?  ``KCCAP_MEMLEDGER=0`` is the dedicated hatch;
    ``KCCAP_TELEMETRY=0`` disables it too (the book rides the telemetry
    substrate and must cost nothing when that is off)."""
    if os.environ.get("KCCAP_MEMLEDGER", "1") == "0":
        return False
    from kubernetesclustercapacity_tpu.telemetry.metrics import (
        enabled as _telemetry_enabled,
    )

    return _telemetry_enabled()


def _leaves(value) -> list:
    """Flatten a staged container into its array leaves (tuples/lists
    nest; anything with ``nbytes`` is a leaf; the rest is ignored —
    staging sites store tuples of jax arrays by construction)."""
    out: list = []
    stack = [value]
    while stack:
        v = stack.pop()
        if isinstance(v, (tuple, list)):
            stack.extend(v)
        elif hasattr(v, "nbytes"):
            out.append(v)
    return out


class DeviceLedger:
    """The process-wide device-byte book (thread-safe; all mutable state
    under ``self._lock`` — hammered by ``analysis/hammer.py``).

    Entries are keyed on the *container's* id: the same object a cache
    stores is the same object it later evicts, so identity is exact.
    Per-leaf ``(id, nbytes)`` pairs are captured at registration for the
    reconciler; no strong references are taken (see module docstring).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # container id -> (form, total_nbytes, ((leaf_id, nbytes), ...))
        self._entries: dict[int, tuple] = {}
        self._by_form: dict[str, int] = {}
        self._total = 0
        self._peak = 0
        self._registered = 0
        self._retired = 0
        self._budget: int | None = None
        self._suspects: set[int] = set()
        self._leaked_bytes = 0
        self._reconciles = 0
        self._alert = WatchAlert(name="device_memory", min_replicas=0)
        self._gauge_forms: set[str] = set()

    # -- write side (the staging sites) ------------------------------

    def register(self, value, form: str) -> int:
        """Book ``value`` (a staged container) under ``form``; returns
        the byte count booked.  Re-registering the same container id
        replaces the previous entry (double-build races in the devcache
        store last-wins — so does the book)."""
        form = str(form)
        leaves = _leaves(value)
        pairs = tuple((id(a), int(a.nbytes)) for a in leaves)
        nbytes = sum(n for _, n in pairs)
        key = id(value)
        with self._lock:
            prev = self._entries.get(key)
            if prev is not None:
                self._by_form[prev[0]] -= prev[1]
                self._total -= prev[1]
                self._retired += 1
            self._entries[key] = (form, nbytes, pairs)
            self._by_form[form] = self._by_form.get(form, 0) + nbytes
            self._total += nbytes
            self._registered += 1
            if self._total > self._peak:
                self._peak = self._total
        self._ensure_gauges(form)
        return nbytes

    def retire(self, value) -> int:
        """Unbook a container at the moment it leaves its cache;
        returns the bytes released (0 for a container never booked —
        retiring twice is harmless, staying booked forever is the bug
        the reconciler exists to catch)."""
        key = id(value)
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is None:
                return 0
            form, nbytes, _ = entry
            self._by_form[form] -= nbytes
            self._total -= nbytes
            self._retired += 1
            return nbytes

    def set_budget(self, nbytes: int | None) -> None:
        with self._lock:
            self._budget = int(nbytes) if nbytes else None

    def reset(self) -> None:
        """Forget everything (tests and the hammer's cleanup)."""
        with self._lock:
            self._entries.clear()
            self._by_form.clear()
            self._total = 0
            self._peak = 0
            self._registered = 0
            self._retired = 0
            self._suspects = set()
            self._leaked_bytes = 0
            self._reconciles = 0
            self._alert = WatchAlert(name="device_memory", min_replicas=0)

    # -- read side ---------------------------------------------------

    def total_bytes(self) -> int:
        with self._lock:
            return self._total

    def form_bytes(self, form: str) -> int:
        with self._lock:
            return self._by_form.get(form, 0)

    def peak_bytes(self) -> int:
        with self._lock:
            return self._peak

    def budget_breached(self) -> bool:
        with self._lock:
            return self._budget is not None and self._total > self._budget

    def leaking(self) -> bool:
        """True while the last reconcile found a SUSTAINED discrepancy
        (the alert is in its breached state)."""
        with self._lock:
            return self._alert.state == "breached"

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": enabled(),
                "total_bytes": self._total,
                "peak_bytes": self._peak,
                "by_form": dict(self._by_form),
                "entries": len(self._entries),
                "registered": self._registered,
                "retired": self._retired,
                "budget_bytes": self._budget,
                "budget_breached": (
                    self._budget is not None and self._total > self._budget
                ),
                "reconciles": self._reconciles,
                "leaked_bytes": self._leaked_bytes,
                "leak_alert": self._alert.to_wire(),
            }

    # -- reconciliation ----------------------------------------------

    def reconcile(self, live_arrays=None) -> dict:
        """Audit the book against the backend's own accounting.

        ``live_arrays`` defaults to ``jax.live_arrays()``; tests inject
        their own.  Every tracked leaf must be identity-present among
        the live arrays; a leaf missing on TWO consecutive reconciles is
        counted as leaked bytes and trips the leak alert (one miss is a
        suspect only — a concurrent eviction between our snapshot and
        jax's walk must not page anyone).  Returns the audit dict.
        """
        if live_arrays is None:
            import jax

            live_arrays = jax.live_arrays()
        live_ids = {id(a) for a in live_arrays}
        with self._lock:
            missing: set[int] = set()
            missing_bytes = 0
            sustained_bytes = 0
            for form, nbytes, pairs in self._entries.values():
                for leaf_id, leaf_bytes in pairs:
                    if leaf_id in live_ids:
                        continue
                    missing.add(leaf_id)
                    missing_bytes += leaf_bytes
                    if leaf_id in self._suspects:
                        sustained_bytes += leaf_bytes
            self._reconciles += 1
            self._suspects = missing
            self._leaked_bytes = sustained_bytes
            # WatchAlert breaches on total < min_replicas: feed the
            # negated discrepancy so "any sustained leaked byte" is the
            # breach and zero is healthy.
            transition = self._alert.update(
                -sustained_bytes, self._reconciles
            )
            return {
                "live_arrays": len(live_ids),
                "tracked_entries": len(self._entries),
                "tracked_bytes": self._total,
                "missing_bytes": missing_bytes,
                "sustained_missing_bytes": sustained_bytes,
                "leaking": self._alert.state == "breached",
                "transition": transition,
            }

    # -- gauges ------------------------------------------------------

    def _ensure_gauges(self, form: str) -> None:
        """Idempotently attach the callback gauges (per-form on first
        sight of the form; peak once).  Outside the lock — registry
        callbacks must never nest under ledger state."""
        if not enabled():
            return
        with self._lock:
            if form in self._gauge_forms:
                return
            first = not self._gauge_forms
            self._gauge_forms.add(form)
        from kubernetesclustercapacity_tpu.telemetry.metrics import (
            REGISTRY,
        )

        g = REGISTRY.gauge(
            "kccap_device_bytes",
            "Live device bytes booked by the memory ledger, by staged "
            "form.",
            ("form",),
        )
        g.labels(form=form).set_function(
            lambda f=form: float(self.form_bytes(f))
        )
        if first:
            REGISTRY.gauge(
                "kccap_device_peak_bytes",
                "High-watermark of ledger-booked device bytes since "
                "process start.",
            ).labels().set_function(lambda: float(self.peak_bytes()))


#: The process-wide book every staging site writes.
LEDGER = DeviceLedger()


def register(value, form: str) -> None:
    """Module-level hook the staging sites call (no-op when the ledger
    is off — the zero-registry-call rule)."""
    if enabled():
        LEDGER.register(value, form)


def retire(value) -> None:
    """Unconditional, unlike :func:`register` — a buffer booked while
    the ledger was armed must come OFF the book even if the hatch has
    since been thrown (a hatch flip mid-process would otherwise turn
    every retirement into a stale leaf, i.e. a false sustained leak).
    Pure bookkeeping: touches no registry, so the zero-registry-call
    pin for the off state still holds."""
    LEDGER.retire(value)


def device_memory_status() -> str:
    """The doctor's "device memory" line: FAILED on a sustained leak or
    a breached budget, soft otherwise."""
    if not enabled():
        return (
            "off (KCCAP_MEMLEDGER=0 or KCCAP_TELEMETRY=0) — device "
            "bytes unaudited"
        )
    st = LEDGER.stats()
    mib = st["total_bytes"] / (1 << 20)
    peak = st["peak_bytes"] / (1 << 20)
    forms = " ".join(
        f"{f}={b / (1 << 20):.1f}MiB"
        for f, b in sorted(st["by_form"].items())
        if b
    )
    if st["leak_alert"]["state"] == "breached":
        return (
            f"FAILED: device-memory leak — {st['leaked_bytes']} "
            "booked byte(s) missing from jax.live_arrays() on "
            "consecutive reconciles; "
            f"live={mib:.1f}MiB peak={peak:.1f}MiB"
        )
    if st["budget_breached"]:
        return (
            f"FAILED: device budget breached — live {mib:.1f}MiB over "
            f"budget {st['budget_bytes'] / (1 << 20):.1f}MiB"
        )
    return (
        f"ok: live={mib:.1f}MiB peak={peak:.1f}MiB "
        f"entries={st['entries']} "
        f"registered={st['registered']} retired={st['retired']}"
        + (f" [{forms}]" if forms else "")
    )
