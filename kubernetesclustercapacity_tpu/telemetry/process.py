"""Process self-telemetry: RSS, fds, threads, GC work, build identity.

Long-running serving processes (kccap-server, kccap-fed, plane
replicas) register these once at start so every scrape answers the
first questions of any incident review — is the process leaking
memory, leaking file descriptors, or spawning threads — plus *which
build* is answering, without shelling into the box:

* ``kccap_process_rss_bytes``           resident set size
* ``kccap_process_open_fds``            open file descriptors
* ``kccap_process_threads``             live Python threads
* ``kccap_process_gc_collections_total`` cumulative GC collections
* ``kccap_build_info``                  constant 1, ``version`` label

All five are CALLBACK gauges: the scrape reads the current value, no
background ticker, no per-request cost.  Registration is idempotent
(same registry semantics as every other family) and a no-op under
``KCCAP_TELEMETRY=0`` — a silenced process must stay silent.

Sources are stdlib-only with graceful degradation: ``/proc/self`` where
it exists (Linux), ``resource.getrusage`` fallback for RSS, ``-1`` for
genuinely unknowable values (a gauge that lies with 0 would read as "no
leak" — ``-1`` reads as "cannot tell").
"""

from __future__ import annotations

import gc
import os
import threading

__all__ = ["register_process_metrics", "rss_bytes", "open_fds"]


def rss_bytes() -> float:
    """Resident set size in bytes, or -1.0 when unknowable."""
    try:
        with open("/proc/self/statm", encoding="ascii") as fh:
            pages = int(fh.read().split()[1])
        return float(pages * os.sysconf("SC_PAGE_SIZE"))
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource

        ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # Linux reports KiB; macOS reports bytes.  Either way it is the
        # peak, not current — an acceptable degraded answer.
        import sys

        return float(ru if sys.platform == "darwin" else ru * 1024)
    except Exception:  # noqa: BLE001 - telemetry degrades, never raises
        return -1.0


def open_fds() -> float:
    """Open file-descriptor count, or -1.0 when unknowable."""
    try:
        return float(len(os.listdir("/proc/self/fd")))
    except OSError:
        return -1.0


def _gc_collections() -> float:
    try:
        return float(sum(s.get("collections", 0) for s in gc.get_stats()))
    except Exception:  # noqa: BLE001 - telemetry degrades, never raises
        return -1.0


def register_process_metrics(registry, *, version: str | None = None):
    """Bind the process gauges onto ``registry``.  Returns the registry
    (chaining convenience) — or unchanged, untouched, when telemetry is
    globally off.  ``version`` defaults to the package version; it lands
    as the ``kccap_build_info`` info-gauge's label, the Prometheus
    idiom for joining every other series to a build."""
    from kubernetesclustercapacity_tpu.telemetry.metrics import (
        enabled as _telemetry_enabled,
    )

    if not _telemetry_enabled() or registry is None:
        return registry
    if version is None:
        from kubernetesclustercapacity_tpu import __version__ as version

    registry.gauge(
        "kccap_process_rss_bytes",
        "Resident set size of this process (bytes; -1 = unknowable).",
    ).labels().set_function(rss_bytes)
    registry.gauge(
        "kccap_process_open_fds",
        "Open file descriptors held by this process (-1 = unknowable).",
    ).labels().set_function(open_fds)
    registry.gauge(
        "kccap_process_threads",
        "Live Python threads in this process.",
    ).labels().set_function(lambda: float(threading.active_count()))
    registry.gauge(
        "kccap_process_gc_collections_total",
        "Cumulative garbage-collector collections (all generations).",
    ).labels().set_function(_gc_collections)
    registry.gauge(
        "kccap_build_info",
        "Constant 1; the version label identifies the running build.",
        ("version",),
    ).labels(version=str(version)).set(1)
    return registry
