"""Cross-process trace context, span emission, and tail-based sampling.

The single-hop tracing of :mod:`.tracing` (client attempt → server
dispatch) generalizes here into Dapper-style causal tracing for the
whole multi-process topology: ReplicaSet failover/hedging, federation
fan-out, plane replication, micro-batch folding.  Three pieces:

* :class:`TraceContext` — the W3C-traceparent-shaped context (trace id,
  current span id, sampled flag, hop count) every wire hop carries.  It
  rides the protocol envelope as plain additive fields
  (:data:`WIRE_FIELDS`), exactly the way ``deadline`` already does, so
  old servers ignore it and old clients never send it.
* :func:`span` — the ONE span-emission call every layer uses.  Field
  names are validated against the documented :data:`SPAN_FIELDS`
  vocabulary (the kccap-lint ``surface-span`` walk pins source literals
  against the same set), and emission never raises: tracing observes
  requests, it never fails them.
* :class:`TailSampler` — tail-based sampling over a bounded in-memory
  ring.  IDs are always generated (cheap: one ``os.urandom`` per hop);
  span BODIES are buffered per trace and only flushed to the JSONL sink
  when the end-of-request :meth:`~TailSampler.finish` verdict says the
  request mattered (breached its op's p99, errored, every-Nth, or
  always).  Because the decision happens at request END, the whole tree
  recorded up to that point survives — the defining property of tail
  sampling.

The ``-trace-sample`` grammar (:func:`parse_sample_spec`)::

    always       keep every trace (the pre-sampling behavior; default)
    p99-breach   keep traces whose request latency breached the op's
                 running p99 estimate (and every errored request)
    errors       keep only errored requests
    rate:N       keep every Nth trace (deterministic counter, N >= 1)

A downstream hop whose envelope says ``trace_sampled: true`` is force-
kept regardless of the local predicate — the hop that made the decision
wins, so one trace is never half-retained across processes.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

from kubernetesclustercapacity_tpu.telemetry.tracing import (
    TraceLog,
    new_span_id,
    new_trace_id,
)

__all__ = [
    "MAX_HOPS",
    "SPAN_FIELDS",
    "TailSampler",
    "TraceContext",
    "TraceSampleError",
    "from_wire",
    "parse_sample_spec",
    "span",
]

#: Loop guard: a context that has crossed this many hops stops
#: propagating (the request still runs; only the trace linkage ends).
MAX_HOPS = 32

#: The documented span-record vocabulary.  Every keyword a ``span(...)``
#: call site passes must come from this set — kccap-lint's
#: ``surface-span`` rule and ``test_metric_names.py`` walk the package
#: sources and pin each ``span(`` field literal against it, the same way
#: phase names are pinned to ``phases.PHASES``.
SPAN_FIELDS = frozenset(
    {
        # identity / linkage
        "trace_id", "span_id", "parent_span_id", "links",
        # timing (ts = wall clock at record, start_ts = wall clock at
        # span start, duration_ms = MONOTONIC duration — a wall-clock
        # step mid-span can never produce a negative duration here)
        "ts", "start_ts", "duration_ms",
        # what happened
        "op", "status", "error", "service", "hops",
        # per-layer annotations
        "phase",                       # server phase child spans
        "attempt", "backoff_ms", "attempts",   # client/replicaset
        "endpoint", "hedge", "winner", "failover_reason",  # replicaset
        "batch_size", "leader",        # micro-batcher
        "cluster", "state", "generation",      # federation / plane
        "kind",                        # plane frame kind
    }
)

#: The envelope fields a context occupies on the wire (documented in
#: :mod:`..service.protocol`; excluded from request digests the way
#: ``trace_id`` already is — per-hop noise must not change identity).
WIRE_FIELDS = ("trace_id", "parent_span_id", "trace_sampled", "trace_hops")


class TraceContext:
    """One hop's view of a distributed trace.

    ``span_id`` names the CURRENT span — the one children parent to and
    the one the next wire hop sends as ``parent_span_id``.  ``sampled``
    is the sticky tail-sampling verdict (True once any hop decided to
    keep the trace); ``hops`` counts wire crossings for the
    :data:`MAX_HOPS` loop guard.
    """

    __slots__ = ("trace_id", "span_id", "sampled", "hops")

    def __init__(
        self,
        trace_id: str | None = None,
        span_id: str | None = None,
        *,
        sampled: bool = False,
        hops: int = 0,
    ) -> None:
        self.trace_id = trace_id or new_trace_id()
        self.span_id = span_id or new_span_id()
        self.sampled = bool(sampled)
        self.hops = int(hops)

    def child(self) -> "TraceContext":
        """A fresh span under the same trace (same hop — in-process
        parent/child, e.g. a phase span under its request span)."""
        return TraceContext(
            self.trace_id, sampled=self.sampled, hops=self.hops
        )

    def to_wire(self) -> dict:
        """The envelope fields the NEXT hop should receive: this span
        becomes the remote parent, the hop count advances.  ``{}`` once
        the :data:`MAX_HOPS` guard trips — the request still crosses
        the wire, the trace linkage just stops growing."""
        if self.hops + 1 > MAX_HOPS:
            return {}
        out = {
            "trace_id": self.trace_id,
            "parent_span_id": self.span_id,
            "trace_hops": self.hops + 1,
        }
        if self.sampled:
            out["trace_sampled"] = True
        return out

    def __repr__(self) -> str:  # debugging aid only
        return (
            f"TraceContext(trace_id={self.trace_id!r}, "
            f"span_id={self.span_id!r}, sampled={self.sampled}, "
            f"hops={self.hops})"
        )


def from_wire(msg: dict) -> TraceContext | None:
    """The context a request envelope carried, or ``None`` when the
    caller sent no ``trace_id``.  A fresh span id is minted for THIS
    hop; the envelope's ``parent_span_id`` stays on the message for the
    receiver to record as its span's parent.  Malformed optional fields
    degrade (ignored) rather than refuse — old/foreign callers must not
    lose service over trace metadata."""
    trace_id = msg.get("trace_id")
    if not isinstance(trace_id, str) or not trace_id:
        return None
    hops = msg.get("trace_hops")
    if isinstance(hops, bool) or not isinstance(hops, int) or hops < 0:
        hops = 0
    return TraceContext(
        trace_id,
        sampled=msg.get("trace_sampled") is True,
        hops=min(hops, MAX_HOPS),
    )


def span(sink, **fields) -> None:
    """Emit one span record to ``sink`` (a :class:`~.tracing.TraceLog`,
    a :class:`TailSampler`, or None).  The only sanctioned emission
    call: field names outside :data:`SPAN_FIELDS` are dropped (never
    written, never fatal), and any sink failure is swallowed — a span
    must never fail the request it describes."""
    if sink is None:
        return
    try:
        clean = {k: v for k, v in fields.items() if k in SPAN_FIELDS}
        sink.record(**clean)
    except Exception:  # noqa: BLE001 - tracing never fails the op
        pass


# ---------------------------------------------------------------------------
# Tail-based sampling
# ---------------------------------------------------------------------------
class TraceSampleError(ValueError):
    """A ``-trace-sample`` spec outside the documented grammar."""


def parse_sample_spec(spec: str):
    """Validate a ``-trace-sample`` spec; returns ``(kind, n)`` where
    ``kind`` is one of ``always | p99-breach | errors | rate`` and ``n``
    is the rate divisor (1 except for ``rate:N``)."""
    s = (spec or "").strip()
    if s in ("always", "p99-breach", "errors"):
        return s, 1
    if s.startswith("rate:"):
        arg = s[len("rate:"):]
        if not arg.isdigit() or int(arg) < 1:
            raise TraceSampleError(
                f"bad -trace-sample rate {spec!r} (want rate:N, N >= 1)"
            )
        return "rate", int(arg)
    raise TraceSampleError(
        f"bad -trace-sample {spec!r} "
        "(grammar: always | p99-breach | errors | rate:N)"
    )


#: p99-breach needs this many prior latency samples for an op before the
#: estimate is trusted; below it, nothing breaches (a cold server would
#: otherwise keep everything, defeating the sampler's point).
_P99_MIN_SAMPLES = 30


class TailSampler:
    """Buffer span bodies per trace; flush or drop at request end.

    ``sink`` is the JSONL :class:`~.tracing.TraceLog` kept spans land
    in.  ``spec`` follows the ``-trace-sample`` grammar.  ``latency``
    (optional) is the request-latency histogram family the
    ``p99-breach`` predicate reads (``latency.labels(op=...)``
    snapshots feed :func:`~.slo.estimate_quantile`).

    The ring is bounded two ways: at most ``max_traces`` in-flight
    traces (oldest evicted — their spans drop and count), at most
    ``max_spans_per_trace`` spans per trace (excess drop and count).
    Eviction can only lose a trace whose ``finish`` never came (a
    leaked/abandoned request) — a bounded price for an unbounded-safety
    guarantee.  Thread-safe; the 16-thread hammer in
    ``analysis/hammer.py`` pins exact kept/dropped counts.
    """

    def __init__(
        self,
        sink: TraceLog,
        spec: str = "always",
        *,
        latency=None,
        max_traces: int = 512,
        max_spans_per_trace: int = 256,
        registry=None,
    ) -> None:
        self.kind, self.rate_n = parse_sample_spec(spec)
        self.spec = (spec or "").strip()
        self._sink = sink
        self._latency = latency
        self._max_traces = max(1, int(max_traces))
        self._max_spans = max(1, int(max_spans_per_trace))
        self._lock = threading.Lock()
        self._ring: OrderedDict[str, list] = OrderedDict()
        self._rate_counter = 0
        self.kept_spans = 0
        self.dropped_spans = 0
        self._m_spans = None
        if registry is not None:
            from kubernetesclustercapacity_tpu.telemetry.metrics import (
                enabled as _telemetry_enabled,
            )

            if _telemetry_enabled():
                self._m_spans = registry.counter(
                    "kccap_trace_spans_total",
                    "Tail-sampled span bodies, by end-of-request "
                    "decision (kept = flushed to the trace log, "
                    "dropped = predicate said no or the ring evicted "
                    "the trace).",
                    ("decision",),
                )

    # -- recording ---------------------------------------------------------
    def record(self, **fields) -> None:
        """Buffer one span body under its trace (``always`` writes
        through — there is no decision to wait for).  Spans with no
        trace id cannot be tail-decided; they write through too (the
        pre-sampling behavior for untraced requests)."""
        trace_id = fields.get("trace_id")
        if self.kind == "always" or not trace_id:
            self._sink.record(**fields)
            with self._lock:
                self.kept_spans += 1
            if self._m_spans is not None:
                self._m_spans.labels(decision="kept").inc()
            return
        evicted = None
        dropped_here = 0
        with self._lock:
            buf = self._ring.get(trace_id)
            if buf is None:
                if len(self._ring) >= self._max_traces:
                    _tid, evicted = self._ring.popitem(last=False)
                buf = []
                self._ring[trace_id] = buf
            if len(buf) < self._max_spans:
                buf.append(fields)
            else:
                dropped_here = 1
            dropped = (len(evicted) if evicted else 0) + dropped_here
            self.dropped_spans += dropped
        if dropped and self._m_spans is not None:
            self._m_spans.labels(decision="dropped").inc(dropped)

    # -- the end-of-request verdict ----------------------------------------
    def decide(
        self,
        op: str,
        duration_s: float,
        error: str | None,
        *,
        forced: bool = False,
    ) -> bool:
        """The tail verdict for one finished request.  ``forced`` is the
        sticky upstream decision (envelope ``trace_sampled``) — it
        always wins, so a trace is never half-kept across hops."""
        if forced or self.kind == "always":
            return True
        if self.kind == "errors":
            return error is not None
        if self.kind == "rate":
            with self._lock:
                self._rate_counter += 1
                # Keep the 1st, (N+1)th, (2N+1)th ... — deterministic,
                # and the first trace is always a keeper (a fresh server
                # should never need N requests before any trace exists).
                return (self._rate_counter - 1) % self.rate_n == 0
        # p99-breach: errors always matter; latency matters once the
        # op's histogram has enough history to estimate a p99 at all.
        if error is not None:
            return True
        if self._latency is None:
            return False
        try:
            child = self._latency.labels(op=op)
            snap = child.snapshot()
            if snap["count"] < _P99_MIN_SAMPLES:
                return False
            from kubernetesclustercapacity_tpu.telemetry.slo import (
                estimate_quantile,
            )

            p99 = estimate_quantile(snap["buckets"], snap["count"], 0.99)
        except Exception:  # noqa: BLE001 - sampling must not fail ops
            return False
        return p99 is not None and duration_s > p99

    def finish(self, trace_id: str | None, *, keep: bool) -> None:
        """Flush (keep) or drop the trace's buffered spans.  A trace id
        never buffered (``always`` mode, unknown id) is a no-op."""
        if not trace_id:
            return
        with self._lock:
            buf = self._ring.pop(trace_id, None)
            if buf is None:
                return
            n = len(buf)
            if keep:
                self.kept_spans += n
            else:
                self.dropped_spans += n
        if keep:
            for fields in buf:
                try:
                    self._sink.record(**fields)
                except Exception:  # noqa: BLE001 - see class docstring
                    pass
        if n and self._m_spans is not None:
            self._m_spans.labels(
                decision="kept" if keep else "dropped"
            ).inc(n)

    # -- introspection -----------------------------------------------------
    def stats(self) -> dict:
        """Doctor/info view: the armed policy and the span ledger."""
        with self._lock:
            return {
                "spec": self.spec,
                "buffered_traces": len(self._ring),
                "kept_spans": self.kept_spans,
                "dropped_spans": self.dropped_spans,
            }
