"""Trace assembly and critical-path analysis over per-process span logs.

Every process in the topology (client, ReplicaSet, server, fed,
plane) appends its spans to its OWN JSONL trace log — there is no
collector.  This module is the read side: point it at the log
directories (``kccap -trace-tree TRACE_ID -trace-logs DIR[,DIR...]``)
and it stitches one trace back into a tree and names where the time
went.

Two rules make the assembly trustworthy across machines:

* **Clock-skew tolerance** — the tree is built from parent linkage
  (``parent_span_id``) ONLY.  Wall clocks on different hosts disagree;
  span ordering or nesting is never inferred from ``ts``.  Sibling
  order is log order, which is deterministic per process.
* **Negative durations are evidence, not data** — a span whose
  recorded ``duration_ms`` is negative was written by a wall-clock
  start/end pair that straddled a clock step.  It is flagged
  ``clock_skew`` and the critical path REFUSES to run through it:
  a critical path computed from a poisoned duration would confidently
  name the wrong contributor, which is worse than naming none.

The critical path itself is the classic greedy descent: from the
longest root, repeatedly step into the child with the largest
(monotonic) duration; each step's *self time* is its duration minus the
chosen child's.  Self times aggregate into the ``phases`` vocabulary
(``phase:*`` child spans name themselves; other ops count under their
op name), so the dominating contributor reads in the same terms as the
``kccap_phase_seconds`` histograms — the cross-hop half of the PR-7
decomposition.
"""

from __future__ import annotations

import json
import math
import os

__all__ = [
    "analyze_trace",
    "assemble_tree",
    "critical_path",
    "load_spans",
]

#: Children per node / spans per trace bound: a malicious or corrupt
#: log cannot make assembly quadratic-explode.
_MAX_SPANS = 100_000


def load_spans(paths) -> list[dict]:
    """Read span records from files and/or directories of JSONL logs.

    ``paths`` is an iterable of paths (or one comma-separated string).
    Directories contribute every ``*.jsonl`` file plus one-deep ``.1``
    rotations.  A *span* line is one carrying ``trace_id``, ``span_id``
    and ``duration_ms`` — request-log lines (``latency_ms``), flight
    dumps, and corrupt lines are skipped, never fatal: forensic readers
    must work on the logs that exist, not the logs one wishes existed.
    """
    if isinstance(paths, str):
        paths = [p for p in paths.split(",") if p.strip()]
    files: list[str] = []
    for p in paths:
        p = os.path.expanduser(str(p).strip())
        if os.path.isdir(p):
            for name in sorted(os.listdir(p)):
                if name.endswith(".jsonl") or name.endswith(".jsonl.1"):
                    files.append(os.path.join(p, name))
        elif os.path.exists(p):
            files.append(p)
    spans: list[dict] = []
    for path in files:
        try:
            with open(path, encoding="utf-8") as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if (
                        isinstance(rec, dict)
                        and rec.get("trace_id")
                        and rec.get("span_id")
                        and "duration_ms" in rec
                    ):
                        spans.append(rec)
                        if len(spans) >= _MAX_SPANS:
                            return spans
        except OSError:
            continue
    return spans


def assemble_tree(spans: list[dict], trace_id: str) -> dict:
    """One trace's spans → a parent-linked tree.

    Returns ``{trace_id, found, spans, processes, roots, orphans,
    clock_skew_spans, in_flight}``.  Each node is the span record plus
    a ``children`` list (log order) and, where applicable, a
    ``clock_skew: True`` flag.  A span whose parent never appears in
    any log (the parent's process lost it, or its trace was dropped by
    tail sampling there) is promoted to a root and counted in
    ``orphans`` — present-but-unparented beats silently absent.

    A span carrying the ``duration_ms`` KEY but no usable number
    (``null``/string/NaN — a process that crashed mid-request wrote
    the start of its record but never the end) is an **in-flight**
    span: it is excluded from assembly and named in ``in_flight``.
    Before this rule such a span entered the tree with an implied
    duration of 0, silently zeroing its own step and inflating its
    parent's self time — a poisoned attribution with no warning.
    """
    mine: dict[str, dict] = {}
    in_flight: list[str] = []
    for rec in spans:
        if rec.get("trace_id") != trace_id:
            continue
        dur = rec.get("duration_ms")
        if (
            isinstance(dur, bool)
            or not isinstance(dur, (int, float))
            or not math.isfinite(dur)
        ):
            in_flight.append(str(rec.get("span_id")))
            continue
        node = dict(rec)
        node["children"] = []
        if isinstance(node.get("duration_ms"), (int, float)) and (
            node["duration_ms"] < 0
        ):
            node["clock_skew"] = True
        # Duplicate span ids (a replayed log segment) — last wins, but
        # children already attached survive.
        prev = mine.get(node["span_id"])
        if prev is not None:
            node["children"] = prev["children"]
        mine[node["span_id"]] = node
    roots: list[dict] = []
    orphans = 0
    for node in mine.values():
        parent_id = node.get("parent_span_id")
        parent = mine.get(parent_id) if parent_id else None
        if parent is node:
            parent = None  # self-parenting guard
        if parent is None:
            if parent_id:
                orphans += 1
            roots.append(node)
        else:
            parent["children"].append(node)
    return {
        "trace_id": trace_id,
        "found": bool(mine),
        "spans": len(mine),
        "processes": sorted(
            {
                str(n["service"])
                for n in mine.values()
                if n.get("service")
            }
        ),
        "roots": roots,
        "orphans": orphans,
        "clock_skew_spans": sorted(
            n["span_id"] for n in mine.values() if n.get("clock_skew")
        ),
        "in_flight": sorted(in_flight),
    }


def _dur(node: dict) -> float:
    v = node.get("duration_ms")
    return float(v) if isinstance(v, (int, float)) else 0.0


def _phase_name(node: dict) -> str:
    """The node's name in ``phases`` vocabulary: an explicit ``phase``
    field wins (the server's ``phase:*`` child spans carry one), else
    the op itself — so cross-hop contributors ("client:fed_sweep",
    "rs:attempt") stay distinguishable in the same breakdown."""
    phase = node.get("phase")
    if isinstance(phase, str) and phase:
        return phase
    return str(node.get("op") or "unknown")


def critical_path(tree: dict) -> dict:
    """The greedy longest-duration descent from the longest root.

    Returns ``{refused, path, total_ms, phase_ms, dominant}``.
    ``refused`` is ``"clock_skew"`` when the path would have to run
    through a negative-duration span — those spans are flagged, never
    trusted — and ``"empty"`` for a trace with no spans.  ``dominant``
    names the largest self-time contributor (phases vocabulary) and its
    share of the end-to-end root duration.
    """
    roots = tree.get("roots") or []
    if not roots:
        return {
            "refused": "empty", "path": [], "total_ms": 0.0,
            "phase_ms": {}, "dominant": None,
        }
    root = max(roots, key=_dur)
    if root.get("clock_skew"):
        return {
            "refused": "clock_skew", "path": [], "total_ms": 0.0,
            "phase_ms": {}, "dominant": None,
        }
    path: list[dict] = []
    phase_ms: dict[str, float] = {}
    node = root
    seen: set[int] = set()
    while node is not None and id(node) not in seen:
        seen.add(id(node))
        usable = [
            c for c in node.get("children", ()) if not c.get("clock_skew")
        ]
        if len(usable) != len(node.get("children", ())):
            # The path's honest continuation is unknowable: one of this
            # node's children carries a poisoned duration.  Refuse
            # rather than guess around it.
            return {
                "refused": "clock_skew", "path": [], "total_ms": 0.0,
                "phase_ms": {}, "dominant": None,
            }
        nxt = max(usable, key=_dur) if usable else None
        self_ms = max(0.0, _dur(node) - (_dur(nxt) if nxt else 0.0))
        path.append(
            {
                "span_id": node.get("span_id"),
                "op": node.get("op"),
                "service": node.get("service"),
                "duration_ms": round(_dur(node), 3),
                "self_ms": round(self_ms, 3),
                **(
                    {"status": node["status"]}
                    if node.get("status") not in (None, "ok")
                    else {}
                ),
            }
        )
        name = _phase_name(node)
        phase_ms[name] = phase_ms.get(name, 0.0) + self_ms
        node = nxt
    total = _dur(root)
    dominant = None
    if phase_ms:
        name = max(phase_ms, key=phase_ms.get)
        dominant = {
            "name": name,
            "ms": round(phase_ms[name], 3),
            "share": round(phase_ms[name] / total, 4) if total > 0 else 0.0,
        }
    return {
        "refused": None,
        "path": path,
        "total_ms": round(total, 3),
        "phase_ms": {k: round(v, 3) for k, v in phase_ms.items()},
        "dominant": dominant,
    }


def analyze_trace(paths, trace_id: str) -> dict:
    """Load → assemble → attribute: the ``-trace-tree`` answer.  The
    returned dict is what ``report.trace_{table,json}_report`` render."""
    tree = assemble_tree(load_spans(paths), trace_id)
    tree["critical_path"] = critical_path(tree)
    return tree
