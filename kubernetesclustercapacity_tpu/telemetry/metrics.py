"""Process-wide, thread-safe metrics registry (Prometheus data model).

Three instrument types — :class:`Counter` (monotonic), :class:`Gauge`
(settable, optionally callback-backed), :class:`Histogram` (cumulative
buckets + sum + count) — each a *family* keyed by metric name with
labeled children.  Families are created idempotently through a
:class:`MetricsRegistry`: asking twice for the same name returns the one
family (so a server, a follower and the fused-kernel path can all wire
themselves against the same registry without coordination), while a
re-registration that *disagrees* (different type or label names) raises
— two subsystems silently sharing a name with different meanings is a
corruption, not a convenience.

Label ordering is fixed at family declaration (``labelnames``) and every
child/exposition renders in exactly that order, so scrape output is
deterministic regardless of keyword-argument order at the call site.

Concurrency: each family holds one lock guarding both its child table
and every child's value, so a counter hammered from many threads counts
exactly (see ``tests/test_telemetry.py``).  Nothing here ever calls out
under a lock except gauge callbacks at *collection* time.

The module-level :data:`REGISTRY` is the process-wide default (the CLI,
``bench.py`` and the fused-kernel path use it).  Embedders that need
isolation — every server/follower instance, every test — construct their
own ``MetricsRegistry``.
"""

from __future__ import annotations

import os
import re
import threading

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "MetricsError",
    "DEFAULT_LATENCY_BUCKETS_S",
    "SUB_MS_LATENCY_BUCKETS_S",
    "REGISTRY",
    "enabled",
]

#: Fixed latency buckets (seconds) shared by every request/kernel
#: histogram in the stack: sub-millisecond resolution where the fused
#: kernel lives (~0.5-1 ms per sweep), stretching to 10 s so a wedged
#: dispatch is still binned, then +Inf (implicit).
DEFAULT_LATENCY_BUCKETS_S = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

#: Fine-grained buckets for kernel/phase histograms.  The default
#: ladder's first boundary is 0.5 ms, which flattens the ~0.7 ms
#: fused-path p50 (and every sub-phase of it) into one bucket — useless
#: for phase p50/p99 estimation.  This ladder resolves 10 µs – 1 ms in
#: sub-bucket steps and still reaches 10 s so a wedged phase bins.
SUB_MS_LATENCY_BUCKETS_S = (
    0.00001, 0.000025, 0.00005, 0.0001, 0.00025, 0.0005, 0.00075,
    0.001, 0.0015, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


class MetricsError(ValueError):
    """Invalid metric/label declaration or conflicting re-registration."""


def enabled() -> bool:
    """Process-wide telemetry switch (``KCCAP_TELEMETRY=0`` disables).

    Checked by the *dispatch-side hooks* (e.g. the fused-kernel path) so
    that with telemetry off the hot sweep path makes zero registry
    calls; the registry itself always works — a disabled process can
    still snapshot an (empty) registry.
    """
    return os.environ.get("KCCAP_TELEMETRY", "1") != "0"


def _format_value(v: float) -> str:
    """Prometheus sample formatting: integers bare, floats as repr."""
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    f = float(v)
    return str(int(f)) if f.is_integer() and abs(f) < 2**63 else repr(f)


class _Family:
    """Shared family machinery: label validation + child table."""

    type: str = ""

    def __init__(self, name: str, help: str, labelnames=()) -> None:
        if not _NAME_RE.match(name):
            raise MetricsError(f"invalid metric name {name!r}")
        labelnames = tuple(labelnames)
        for ln in labelnames:
            if not _LABEL_RE.match(ln) or ln.startswith("__"):
                raise MetricsError(f"invalid label name {ln!r}")
        if len(set(labelnames)) != len(labelnames):
            raise MetricsError(f"duplicate label names in {labelnames}")
        self.name = name
        self.help = help
        self.labelnames = labelnames
        self._lock = threading.Lock()
        self._children: dict[tuple[str, ...], object] = {}

    def _child_key(self, labels: dict) -> tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise MetricsError(
                f"{self.name} wants labels {self.labelnames}, "
                f"got {tuple(sorted(labels))}"
            )
        # Values in DECLARATION order — the one ordering every child key,
        # snapshot entry and exposition line shares.
        return tuple(str(labels[ln]) for ln in self.labelnames)

    def labels(self, **labels):
        """The child for this label-value combination (created once)."""
        key = self._child_key(labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._make_child()
            return child

    def _make_child(self):  # pragma: no cover - overridden
        raise NotImplementedError

    def _items(self) -> list[tuple[tuple[str, ...], object]]:
        """Children in insertion order, as a stable copy."""
        with self._lock:
            return list(self._children.items())


class _CounterChild:
    __slots__ = ("_lock", "_value")

    def __init__(self, lock: threading.Lock) -> None:
        self._lock = lock
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise MetricsError(f"counters only go up, got inc({amount})")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Counter(_Family):
    """Monotonic counter family (``_total`` naming is the caller's)."""

    type = "counter"

    def _make_child(self) -> _CounterChild:
        return _CounterChild(self._lock)

    def inc(self, amount: float = 1.0, **labels) -> None:
        self.labels(**labels).inc(amount)

    @property
    def value(self) -> float:
        """Unlabeled convenience (only valid for label-less families)."""
        return self.labels().value


class _GaugeChild:
    __slots__ = ("_lock", "_value", "_fn")

    def __init__(self, lock: threading.Lock) -> None:
        self._lock = lock
        self._value = 0.0
        self._fn = None

    def set(self, value: float) -> None:
        with self._lock:
            self._fn = None
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def set_function(self, fn) -> None:
        """Collect the value by calling ``fn()`` at read time — for state
        that already lives elsewhere (breaker state, queue depths), so
        the gauge can never go stale."""
        with self._lock:
            self._fn = fn

    @property
    def value(self) -> float:
        with self._lock:
            fn = self._fn
            if fn is None:
                return self._value
        # Callback runs OUTSIDE the lock: it may itself take locks
        # (e.g. CircuitBreaker.state) and must not nest under ours.
        return float(fn())


class Gauge(_Family):
    type = "gauge"

    def _make_child(self) -> _GaugeChild:
        return _GaugeChild(self._lock)

    def set(self, value: float, **labels) -> None:
        self.labels(**labels).set(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        self.labels(**labels).inc(amount)

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.labels(**labels).dec(amount)

    @property
    def value(self) -> float:
        return self.labels().value


class _HistogramChild:
    __slots__ = (
        "_lock", "_buckets", "_counts", "_sum", "_count", "_exemplars",
    )

    def __init__(self, lock: threading.Lock, buckets: tuple) -> None:
        self._lock = lock
        self._buckets = buckets
        self._counts = [0] * len(buckets)
        self._sum = 0.0
        self._count = 0
        # Lazily-allocated per-bucket exemplars: bucket index (len(
        # buckets) = the +Inf bucket) -> (trace_id, value, ts).  Memory
        # is bounded by the bucket count — LAST exemplar wins, which is
        # exactly the metrics→traces join an operator wants ("show me a
        # recent trace that landed in this latency bucket").
        self._exemplars: dict | None = None

    def observe(self, value: float, exemplar: str | None = None) -> None:
        value = float(value)
        idx = len(self._buckets)  # +Inf unless a finite bucket matches
        with self._lock:
            self._sum += value
            self._count += 1
            for i, b in enumerate(self._buckets):
                if value <= b:
                    self._counts[i] += 1
                    # Non-cumulative internally; exposition/snapshot
                    # cumulate so one observe is one increment.
                    idx = i
                    break
            if exemplar:
                if self._exemplars is None:
                    self._exemplars = {}
                import time as _time

                self._exemplars[idx] = (
                    str(exemplar), value, _time.time()
                )

    def snapshot(self) -> dict:
        """``{"buckets": {le: cumulative}, "sum": s, "count": n}`` with
        the ``+Inf`` bucket explicit (== count, by construction).  When
        any observation carried an exemplar, an ``"exemplars"`` entry
        maps the bucket's ``le`` string to
        ``{"trace_id", "value", "ts"}``."""
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
            ex = dict(self._exemplars) if self._exemplars else None
        out, acc = {}, 0
        for b, c in zip(self._buckets, counts):
            acc += c
            out[_format_value(b)] = acc
        out["+Inf"] = total
        snap = {"buckets": out, "sum": s, "count": total}
        if ex:
            les = [_format_value(b) for b in self._buckets] + ["+Inf"]
            snap["exemplars"] = {
                les[i]: {"trace_id": t, "value": v, "ts": ts}
                for i, (t, v, ts) in ex.items()
            }
        return snap

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum


def _normalize_buckets(buckets) -> tuple:
    """Canonical bucket tuple: sorted floats, dupes rejected, the
    implicit ``+Inf`` stripped (rendered from count; storing it would
    double-book every observation)."""
    buckets = tuple(sorted(float(b) for b in buckets))
    if not buckets:
        raise MetricsError("histogram needs at least one bucket")
    if buckets != tuple(dict.fromkeys(buckets)):
        raise MetricsError(f"duplicate buckets in {buckets}")
    if buckets[-1] == float("inf"):
        buckets = buckets[:-1]
    return buckets


class Histogram(_Family):
    type = "histogram"

    def __init__(
        self,
        name: str,
        help: str,
        labelnames=(),
        buckets=DEFAULT_LATENCY_BUCKETS_S,
    ) -> None:
        super().__init__(name, help, labelnames)
        if "le" in self.labelnames:
            raise MetricsError("'le' is reserved for histogram buckets")
        self.buckets = _normalize_buckets(buckets)

    def _make_child(self) -> _HistogramChild:
        return _HistogramChild(self._lock, self.buckets)

    def observe(
        self, value: float, exemplar: str | None = None, **labels
    ) -> None:
        self.labels(**labels).observe(value, exemplar=exemplar)


class MetricsRegistry:
    """Thread-safe family registry: create-or-get by name, snapshot all.

    ``counter``/``gauge``/``histogram`` are idempotent per name; a type
    or label-name disagreement raises :class:`MetricsError`.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}

    def _register(self, cls, name, help, labelnames, **kw) -> _Family:
        labelnames = tuple(labelnames)
        with self._lock:
            existing = self._families.get(name)
            if existing is not None:
                if not isinstance(existing, cls) or (
                    existing.labelnames != labelnames
                ):
                    raise MetricsError(
                        f"metric {name!r} already registered as "
                        f"{existing.type}{existing.labelnames}, cannot "
                        f"re-register as {cls.type}{labelnames}"
                    )
                if isinstance(existing, Histogram) and "buckets" in kw:
                    # Custom bucket boundaries are part of the metric's
                    # meaning: two subsystems silently sharing a name
                    # with different ladders would make every p50/p99
                    # estimate a lie about one of them.
                    wanted = _normalize_buckets(kw["buckets"])
                    if wanted != existing.buckets:
                        raise MetricsError(
                            f"histogram {name!r} already registered with "
                            f"buckets {existing.buckets}, cannot "
                            f"re-register with {wanted}"
                        )
                return existing
            fam = cls(name, help, labelnames, **kw)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "", labelnames=()) -> Counter:
        return self._register(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "", labelnames=()) -> Gauge:
        return self._register(Gauge, name, help, labelnames)

    def histogram(
        self,
        name: str,
        help: str = "",
        labelnames=(),
        buckets=DEFAULT_LATENCY_BUCKETS_S,
    ) -> Histogram:
        return self._register(
            Histogram, name, help, labelnames, buckets=buckets
        )

    def collect(self) -> list[_Family]:
        """Families in registration order (stable copy)."""
        with self._lock:
            return list(self._families.values())

    def snapshot(self) -> dict:
        """JSON-able view of every family: the ``info``-op / bench form.

        ``{name: {"type": t, "values": {label_str: value_or_histdict}}}``
        where ``label_str`` is the exposition label block (``""`` for an
        unlabeled child) — so the snapshot and the scrape agree on
        identity.
        """
        out: dict = {}
        for fam in self.collect():
            values: dict = {}
            for key, child in fam._items():
                label_str = ",".join(
                    f'{ln}="{escape_label_value(v)}"'
                    for ln, v in zip(fam.labelnames, key)
                )
                if isinstance(child, _HistogramChild):
                    values[label_str] = child.snapshot()
                else:
                    values[label_str] = child.value
            out[fam.name] = {"type": fam.type, "values": values}
        return out


def escape_label_value(v: str) -> str:
    """Prometheus label-value escaping: backslash, quote, newline."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


#: The process-wide default registry (CLI, bench, fused-kernel path).
REGISTRY = MetricsRegistry()
