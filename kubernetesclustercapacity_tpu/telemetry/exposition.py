"""Prometheus exposition: text format v0.0.4 + a tiny scrape endpoint.

:func:`render_text` turns a :class:`~.metrics.MetricsRegistry` into the
text format every Prometheus-compatible scraper parses — ``# HELP`` /
``# TYPE`` headers, samples with escaped label values in declaration
order, histogram ``_bucket{le=...}`` series cumulative with the
``+Inf`` bucket equal to ``_count``.

:class:`MetricsServer` serves that rendering over HTTP from a
background thread (stdlib ``http.server`` — no new dependencies):

* ``GET /metrics``  — the scrape, ``text/plain; version=0.0.4``;
* ``GET /healthz``  — liveness JSON; an embedder-supplied ``healthy``
  callable flips it to 503 (e.g. a dead follower behind a serving
  snapshot must be *visible* to the load balancer, the same
  never-silently-stale rule the follower itself enforces).

The endpoint is observability-only and carries no auth: bind it to
localhost (the default) or scrape-net, never the request port.
"""

from __future__ import annotations

import json
import threading

from kubernetesclustercapacity_tpu.telemetry.metrics import (
    MetricsRegistry,
    _format_value,
    _HistogramChild,
    escape_label_value,
)

__all__ = ["render_text", "MetricsServer", "start_metrics_server"]

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _label_block(labelnames, key, extra: str = "") -> str:
    """``{a="x",b="y"}`` in declaration order; ``""`` when empty."""
    parts = [
        f'{ln}="{escape_label_value(v)}"'
        for ln, v in zip(labelnames, key)
    ]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def render_text(registry: MetricsRegistry) -> str:
    """The registry as Prometheus text format v0.0.4 (one scrape body)."""
    lines: list[str] = []
    for fam in registry.collect():
        lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
        lines.append(f"# TYPE {fam.name} {fam.type}")
        for key, child in fam._items():
            if isinstance(child, _HistogramChild):
                snap = child.snapshot()
                for le, cum in snap["buckets"].items():
                    le_pair = 'le="%s"' % le
                    lines.append(
                        f"{fam.name}_bucket"
                        f"{_label_block(fam.labelnames, key, le_pair)}"
                        f" {_format_value(cum)}"
                    )
                lines.append(
                    f"{fam.name}_sum{_label_block(fam.labelnames, key)}"
                    f" {_format_value(snap['sum'])}"
                )
                lines.append(
                    f"{fam.name}_count{_label_block(fam.labelnames, key)}"
                    f" {_format_value(snap['count'])}"
                )
            else:
                lines.append(
                    f"{fam.name}{_label_block(fam.labelnames, key)}"
                    f" {_format_value(child.value)}"
                )
    return "\n".join(lines) + ("\n" if lines else "")


class MetricsServer:
    """Background-thread HTTP endpoint for ``/metrics`` + ``/healthz``.

    ``healthy`` is an optional zero-arg callable returning truthy when
    the embedding process considers itself live; a raise counts as
    unhealthy (a health check that can crash the server it reports on
    would be worse than no check).

    ``status`` is an optional zero-arg callable returning a JSON-able
    dict merged into the ``/healthz`` body — the embedder's freshness
    evidence (snapshot generation, follower last-relist age) so a load
    balancer can detect a *stuck* follower behind a liveness check that
    still answers.  A raise surfaces as ``{"status_error": ...}`` and
    flips the reply to 503: a status source that cannot report is
    indistinguishable from a wedged feed.
    """

    def __init__(
        self,
        registry: MetricsRegistry,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        healthy=None,
        status=None,
    ) -> None:
        import http.server

        self.registry = registry
        self._healthy = healthy
        self._status = status
        outer = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 - stdlib contract
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = render_text(outer.registry).encode()
                    self._reply(200, CONTENT_TYPE, body)
                elif path == "/healthz":
                    ok = True
                    if outer._healthy is not None:
                        try:
                            ok = bool(outer._healthy())
                        except Exception:  # noqa: BLE001 - check != crash
                            ok = False
                    payload = {"ok": ok}
                    if outer._status is not None:
                        try:
                            payload.update(outer._status() or {})
                        except Exception as e:  # noqa: BLE001 - see class doc
                            ok = False
                            payload["ok"] = False
                            payload["status_error"] = (
                                f"{type(e).__name__}: {e}"
                            )
                    body = json.dumps(payload).encode()
                    self._reply(200 if ok else 503, "application/json", body)
                else:
                    self._reply(404, "text/plain", b"not found\n")

            def _reply(self, code: int, ctype: str, body: bytes) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args) -> None:  # scrapes are not news
                pass

        class _Server(http.server.ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._http = _Server((host, port), _Handler)
        self._thread: threading.Thread | None = None

    @property
    def address(self) -> tuple[str, int]:
        return self._http.server_address  # type: ignore[return-value]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def start(self) -> "MetricsServer":
        self._thread = threading.Thread(
            target=self._http.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self._http.shutdown()
        self._http.server_close()


def start_metrics_server(
    registry: MetricsRegistry,
    *,
    host: str = "127.0.0.1",
    port: int = 0,
    healthy=None,
    status=None,
) -> MetricsServer:
    """Construct AND start a :class:`MetricsServer` (the one-liner every
    embedder wants; ``port=0`` picks a free port — read ``.address``)."""
    return MetricsServer(
        registry, host=host, port=port, healthy=healthy, status=status
    ).start()
