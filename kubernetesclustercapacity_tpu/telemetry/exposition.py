"""Prometheus exposition: text format v0.0.4 + a tiny scrape endpoint.

:func:`render_text` turns a :class:`~.metrics.MetricsRegistry` into the
text format every Prometheus-compatible scraper parses — ``# HELP`` /
``# TYPE`` headers, samples with escaped label values in declaration
order, histogram ``_bucket{le=...}`` series cumulative with the
``+Inf`` bucket equal to ``_count``.

:class:`MetricsServer` serves that rendering over HTTP from a
background thread (stdlib ``http.server`` — no new dependencies):

* ``GET /metrics``  — the scrape, ``text/plain; version=0.0.4`` with an
  explicit charset; the endpoint self-reports
  ``kccap_scrape_duration_seconds`` (how long each rendering took), so
  a scrape config's timeout budget is tunable from the scrapes
  themselves;
* ``GET /healthz``  — liveness JSON; an embedder-supplied ``healthy``
  callable flips it to 503 (e.g. a dead follower behind a serving
  snapshot must be *visible* to the load balancer, the same
  never-silently-stale rule the follower itself enforces).

``HEAD`` is answered on every path with the GET status/headers and no
body — uptime probes and load balancers preflight with HEAD, and an
observability endpoint that 501s them reads as down.

The endpoint is observability-only and carries no auth: bind it to
localhost (the default) or scrape-net, never the request port.
"""

from __future__ import annotations

import json
import threading

from kubernetesclustercapacity_tpu.telemetry.metrics import (
    MetricsRegistry,
    _format_value,
    _HistogramChild,
    escape_label_value,
)

__all__ = ["render_text", "MetricsServer", "start_metrics_server"]

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _label_block(labelnames, key, extra: str = "") -> str:
    """``{a="x",b="y"}`` in declaration order; ``""`` when empty."""
    parts = [
        f'{ln}="{escape_label_value(v)}"'
        for ln, v in zip(labelnames, key)
    ]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _exemplar_suffix(ex: dict | None, le: str) -> str:
    """The OpenMetrics exemplar tail for one bucket sample —
    `` # {trace_id="..."} value ts`` — or ``""`` when the bucket never
    carried one.  Classic v0.0.4 parsers that split on the LAST space
    still read the line once they strip the `` # `` comment tail (the
    test-side ``parse_exposition`` does exactly that)."""
    if not ex:
        return ""
    entry = ex.get(le)
    if entry is None:
        return ""
    return (
        f' # {{trace_id="{escape_label_value(entry["trace_id"])}"}}'
        f' {_format_value(entry["value"])} {entry["ts"]:.3f}'
    )


def render_text(registry: MetricsRegistry) -> str:
    """The registry as Prometheus text format v0.0.4 (one scrape body).
    Histogram buckets that recorded an exemplar carry it in OpenMetrics
    exemplar syntax — the metrics→traces join, no grepping required."""
    lines: list[str] = []
    for fam in registry.collect():
        lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
        lines.append(f"# TYPE {fam.name} {fam.type}")
        for key, child in fam._items():
            if isinstance(child, _HistogramChild):
                snap = child.snapshot()
                exemplars = snap.get("exemplars")
                for le, cum in snap["buckets"].items():
                    le_pair = 'le="%s"' % le
                    lines.append(
                        f"{fam.name}_bucket"
                        f"{_label_block(fam.labelnames, key, le_pair)}"
                        f" {_format_value(cum)}"
                        f"{_exemplar_suffix(exemplars, le)}"
                    )
                lines.append(
                    f"{fam.name}_sum{_label_block(fam.labelnames, key)}"
                    f" {_format_value(snap['sum'])}"
                )
                lines.append(
                    f"{fam.name}_count{_label_block(fam.labelnames, key)}"
                    f" {_format_value(snap['count'])}"
                )
            else:
                lines.append(
                    f"{fam.name}{_label_block(fam.labelnames, key)}"
                    f" {_format_value(child.value)}"
                )
    return "\n".join(lines) + ("\n" if lines else "")


class MetricsServer:
    """Background-thread HTTP endpoint for ``/metrics`` + ``/healthz``.

    ``healthy`` is an optional zero-arg callable returning truthy when
    the embedding process considers itself live; a raise counts as
    unhealthy (a health check that can crash the server it reports on
    would be worse than no check).

    ``status`` is an optional zero-arg callable returning a JSON-able
    dict merged into the ``/healthz`` body — the embedder's freshness
    evidence (snapshot generation, follower last-relist age) so a load
    balancer can detect a *stuck* follower behind a liveness check that
    still answers.  A raise surfaces as ``{"status_error": ...}`` and
    flips the reply to 503: a status source that cannot report is
    indistinguishable from a wedged feed.

    ``debug`` is an optional ``{path: handler}`` map of extra GET
    endpoints (e.g. ``/debug/profile``); each handler takes the raw
    query string and returns ``(content_type, body_bytes)``.  Handlers
    run on the request's own thread (the threading server means a
    handler that sleeps — the profiler's collection window — blocks
    only its caller, never scrapes).  A raising handler is a 500 with
    the error named, same crash-isolation rule as ``healthy``.
    """

    def __init__(
        self,
        registry: MetricsRegistry,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        healthy=None,
        status=None,
        debug=None,
    ) -> None:
        import http.server
        import time

        from kubernetesclustercapacity_tpu.telemetry.metrics import (
            enabled as _telemetry_enabled,
        )

        self.registry = registry
        self._healthy = healthy
        self._status = status
        self._debug = dict(debug or {})
        # Scrape self-report: the time each exposition render takes,
        # visible in the very scrape it measures (the previous render's
        # sample — a scrape cannot carry its own final timing).  Skipped
        # under KCCAP_TELEMETRY=0: a disabled process must not have its
        # metrics endpoint re-populate the registry it silenced.
        self._scrape_hist = (
            registry.histogram(
                "kccap_scrape_duration_seconds",
                "Time spent rendering the /metrics exposition.",
            )
            if _telemetry_enabled()
            else None
        )
        outer = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 - stdlib contract
                self._serve(head=False)

            def do_HEAD(self) -> None:  # noqa: N802 - stdlib contract
                # Identical routing/status/headers, body withheld: the
                # cheap liveness preflight probes and LBs issue.
                self._serve(head=True)

            def _serve(self, *, head: bool) -> None:
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    t0 = time.perf_counter()
                    body = render_text(outer.registry).encode()
                    if outer._scrape_hist is not None:
                        outer._scrape_hist.observe(
                            time.perf_counter() - t0
                        )
                    self._reply(200, CONTENT_TYPE, body, head)
                elif path == "/healthz":
                    ok = True
                    if outer._healthy is not None:
                        try:
                            ok = bool(outer._healthy())
                        except Exception:  # noqa: BLE001 - check != crash
                            ok = False
                    payload = {"ok": ok}
                    if outer._status is not None:
                        try:
                            payload.update(outer._status() or {})
                        except Exception as e:  # noqa: BLE001 - see class doc
                            ok = False
                            payload["ok"] = False
                            payload["status_error"] = (
                                f"{type(e).__name__}: {e}"
                            )
                    body = json.dumps(payload).encode()
                    self._reply(
                        200 if ok else 503,
                        "application/json; charset=utf-8",
                        body,
                        head,
                    )
                elif path in outer._debug:
                    query = (
                        self.path.split("?", 1)[1]
                        if "?" in self.path
                        else ""
                    )
                    try:
                        ctype, body = outer._debug[path](query)
                    except Exception as e:  # noqa: BLE001 - see class doc
                        self._reply(
                            500,
                            "text/plain; charset=utf-8",
                            f"{type(e).__name__}: {e}\n".encode(),
                            head,
                        )
                        return
                    self._reply(200, ctype, body, head)
                else:
                    self._reply(
                        404, "text/plain; charset=utf-8", b"not found\n",
                        head,
                    )

            def _reply(
                self, code: int, ctype: str, body: bytes,
                head: bool = False,
            ) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if not head:
                    self.wfile.write(body)

            def log_message(self, *args) -> None:  # scrapes are not news
                pass

        class _Server(http.server.ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._http = _Server((host, port), _Handler)
        self._thread: threading.Thread | None = None

    @property
    def address(self) -> tuple[str, int]:
        return self._http.server_address  # type: ignore[return-value]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def start(self) -> "MetricsServer":
        self._thread = threading.Thread(
            target=self._http.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self._http.shutdown()
        self._http.server_close()


def start_metrics_server(
    registry: MetricsRegistry,
    *,
    host: str = "127.0.0.1",
    port: int = 0,
    healthy=None,
    status=None,
    debug=None,
) -> MetricsServer:
    """Construct AND start a :class:`MetricsServer` (the one-liner every
    embedder wants; ``port=0`` picks a free port — read ``.address``)."""
    return MetricsServer(
        registry, host=host, port=port, healthy=healthy, status=status,
        debug=debug,
    ).start()
