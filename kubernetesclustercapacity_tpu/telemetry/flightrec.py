"""Flight recorder: a thread-safe ring buffer of the last K requests.

Metrics say HOW MUCH; traces say how long ONE request took; the flight
recorder answers the post-incident question neither can: *what exactly
were the last K things this server was asked to do before it broke?*
Each record is small and fixed-shape — op, a digest of the request
arguments (never the arguments themselves: requests can carry tokens and
multi-MB grids), the snapshot generation it ran against, the caller's
trace ID, latency, status, and a digest of the result — so the ring
costs O(K) memory forever and can be dumped as JSONL at any moment:
on server error (``-flight-dump``), over the wire (the ``dump`` op),
or from ``kccap -doctor -doctor-service``.

Digests are truncated SHA-256 over canonical JSON with the secret-bearing
envelope fields (``token``) stripped.  Two requests with identical
arguments share a digest, which is exactly what replay-style debugging
wants ("the same sweep, 400 times, then the crash").
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from collections import deque

__all__ = ["FlightRecorder", "args_digest", "result_digest"]

#: Envelope fields never folded into a digest: secrets (the shared
#: ``token`` AND the per-tenant ``tenant_token`` — a per-tenant secret
#: is still a secret), and fields that vary per attempt without
#: changing what the request MEANS (the whole trace-context envelope:
#: ids, the remote parent span, the sampling verdict, the hop count).
_DIGEST_EXCLUDED = (
    "token", "tenant_token", "trace_id", "deadline",
    "parent_span_id", "trace_sampled", "trace_hops",
)

_DIGEST_HEX = 16  # 64 bits of SHA-256 — plenty for correlation, tiny on disk


def _digest(obj) -> str:
    try:
        blob = json.dumps(obj, sort_keys=True, default=repr)
    except (TypeError, ValueError):
        blob = repr(obj)
    return hashlib.sha256(blob.encode()).hexdigest()[:_DIGEST_HEX]


def args_digest(msg: dict) -> str:
    """Digest of a request message, secrets/envelope noise stripped."""
    return _digest(
        {k: v for k, v in msg.items() if k not in _DIGEST_EXCLUDED}
    )


def result_digest(result) -> str:
    """Digest of an op result (any JSON-able shape)."""
    return _digest(result)


class FlightRecorder:
    """Bounded in-memory request history, safe for concurrent dispatch.

    ``capacity`` is the K of "the last K requests"; older records fall
    off the far end (``dropped`` counts them, so a dump can say how much
    history it does NOT contain).  ``record`` never raises on behalf of
    the request it observes — recording is observability, not dispatch.
    """

    def __init__(self, capacity: int = 256) -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._ring: deque[dict] = deque(maxlen=self.capacity)
        self._seq = 0
        self._dropped = 0

    def record(
        self,
        *,
        op: str,
        args_digest: str,
        generation: int,
        trace_id: str = "",
        latency_ms: float,
        status: str,
        result_digest: str = "",
        error: str | None = None,
        ts: float | None = None,
        audit_ref: str | None = None,
        phases: dict | None = None,
        tenant: str = "",
        trace_sampled: bool | None = None,
    ) -> None:
        """``audit_ref`` — the ``segment:offset`` pointer into the
        server's audit log for this same request (when auditing is on),
        so a ``dump`` record pastes straight into ``kccap -replay
        DIR -replay-ref REF``.  ``phases`` — the request's per-phase
        latency decomposition (``{phase: ms}``, the
        :class:`~.phases.PhaseClock`'s compact form), so a slow request
        pasted from a dump is self-explaining.  ``tenant`` — the DERIVED
        tenant identity (never a token); empty when tenancy is off, and
        then absent from the record so pre-tenancy dumps are unchanged.
        ``trace_sampled`` — the tail-sampling verdict for this request
        (True = its full span tree was retained in the trace log), so a
        ``-replay`` of a divergence knows whether a trace exists for it;
        ``None`` (no sampler armed) keeps the record shape unchanged."""
        rec = {
            "seq": 0,  # assigned under the lock
            "ts": time.time() if ts is None else ts,
            "op": op,
            "args_digest": args_digest,
            "generation": int(generation),
            "trace_id": trace_id or "",
            "latency_ms": round(float(latency_ms), 3),
            "status": status,
            "result_digest": result_digest,
        }
        if tenant:
            rec["tenant"] = tenant
        if trace_sampled is not None:
            rec["trace_sampled"] = bool(trace_sampled)
        if error:
            rec["error"] = error
        if audit_ref:
            rec["audit_ref"] = audit_ref
        if phases:
            rec["phases"] = {
                str(k): round(float(v), 3) for k, v in phases.items()
            }
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            if len(self._ring) == self.capacity:
                self._dropped += 1
            self._ring.append(rec)

    def records(self) -> list[dict]:
        """Oldest-to-newest copy of the ring (records are fresh dicts —
        callers can mutate without corrupting the recorder)."""
        with self._lock:
            return [dict(r) for r in self._ring]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    @property
    def dropped(self) -> int:
        """Records pushed off the far end since construction/clear."""
        with self._lock:
            return self._dropped

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._dropped = 0

    def dump_jsonl(self, path: str) -> int:
        """Append the ring to ``path`` as JSONL; returns lines written.

        Append (not truncate): successive error dumps accumulate rather
        than overwrite the history that preceded the first failure.
        Each dump is framed by a header line carrying the drop count, so
        a reader can tell dumps apart and knows how much history the
        ring had already forgotten.
        """
        records = self.records()
        with self._lock:
            dropped = self._dropped
        with open(path, "a", encoding="utf-8") as fh:
            fh.write(
                json.dumps(
                    {
                        "flight_dump": True,
                        "ts": time.time(),
                        "records": len(records),
                        "dropped": dropped,
                        "capacity": self.capacity,
                    },
                    sort_keys=True,
                )
                + "\n"
            )
            for rec in records:
                fh.write(json.dumps(rec, sort_keys=True) + "\n")
        return len(records) + 1
