"""JAX compile visibility: first-call (compile) vs steady-state latency.

A jitted kernel's first dispatch pays trace + XLA/Mosaic compile — on
this stack that is seconds against a sub-millisecond steady state, and a
recompile storm (shape churn, cache eviction) looks exactly like a
latency regression unless the two are tracked apart.  This module is the
one place that split lives: every auto-dispatch entry point
(:func:`..ops.fit.sweep_snapshot`, :func:`..ops.pallas_fit.sweep_auto`,
:func:`..ops.pallas_multi.sweep_multi_auto`) reports its host-timed
dispatch here, and the FIRST observation per kernel label is recorded as
the compile (gauge + counter) while the rest feed a steady-state
histogram.

"First per label" is an approximation of "compiled": jit caches per
(shapes, static args), so a shape change recompiles without showing up
here — honest enough for the scrape's purpose (catching compile-time
regressions round over round; ``bench.py`` records the exact per-shape
compile in its own artifact).

Hot-path rule inherited from the package: everything here is host-side,
after the device sync, and every entry checks
:func:`~.metrics.enabled` — ``KCCAP_TELEMETRY=0`` means zero registry
calls.
"""

from __future__ import annotations

import threading

from kubernetesclustercapacity_tpu.telemetry.metrics import (
    SUB_MS_LATENCY_BUCKETS_S,
    enabled,
)

__all__ = ["observe_dispatch", "seen_kernels", "reset"]

_lock = threading.Lock()
_seen: set[str] = set()
_MET: dict | None = None


def _metrics() -> dict:
    global _MET
    if _MET is None:
        from kubernetesclustercapacity_tpu.telemetry.metrics import REGISTRY

        _MET = {
            "compiles": REGISTRY.counter(
                "kccap_kernel_compiles_total",
                "First-call (trace+compile) dispatches observed, by kernel.",
                ("kernel",),
            ),
            "first_call": REGISTRY.gauge(
                "kccap_kernel_first_call_seconds",
                "Host-timed duration of the kernel's first dispatch "
                "(includes trace + compile), by kernel.",
                ("kernel",),
            ),
            "steady": REGISTRY.histogram(
                "kccap_kernel_steady_seconds",
                "Host-timed steady-state (post-compile) dispatch "
                "latency, by kernel.",
                ("kernel",),
                # Sub-ms ladder (metrics.SUB_MS_LATENCY_BUCKETS_S): the
                # fixed default buckets flatten a ~0.7 ms fused dispatch
                # into one bin, making steady-state p50/p99 useless.
                buckets=SUB_MS_LATENCY_BUCKETS_S,
            ),
        }
    return _MET


def observe_dispatch(kernel: str, seconds: float) -> str:
    """Record one host-timed dispatch of ``kernel``.

    Returns ``"compile"`` for the first observation of this kernel label
    in the process, ``"steady"`` after, ``"disabled"`` when telemetry is
    off (in which case nothing touches the registry).
    """
    if not enabled():
        return "disabled"
    with _lock:
        first = kernel not in _seen
        if first:
            _seen.add(kernel)
    m = _metrics()
    if first:
        m["compiles"].labels(kernel=kernel).inc()
        m["first_call"].labels(kernel=kernel).set(float(seconds))
        return "compile"
    m["steady"].labels(kernel=kernel).observe(float(seconds))
    return "steady"


def seen_kernels() -> tuple[str, ...]:
    """Kernel labels that have dispatched at least once (sorted)."""
    with _lock:
        return tuple(sorted(_seen))


def reset() -> None:
    """Forget which kernels have compiled (tests / operators re-arming
    after a deliberate cache flush).  Registry values are left alone —
    counters are monotonic by contract."""
    with _lock:
        _seen.clear()
