"""Continuous sampling profiler: host flamegraphs joined to the phase
vocabulary.

ROADMAP item 3 ends on a measurement question — "serialization dominates
the folded CPU profile" — that nothing in the repo could actually
produce, attribute, or check.  This module is the instrument: a
supervised background thread samples every live thread's Python stack
(``sys._current_frames()``) at :data:`DEFAULT_HZ` (``-profile-hz`` /
``KCCAP_PROFILE_HZ``), folds each stack into a collapsed-flamegraph
line (Brendan Gregg's ``frame;frame;frame count`` format, root first),
and prefixes each line with the sampled thread's live ``(op, tenant,
phase)`` attribution from :func:`~.phases.live_snapshot` — so "which
frames inside ``serialize``?" is one grep, and the dominant phase of a
profile can be reconciled against the ``kccap_phase_seconds`` histogram.

Surfaces:

* ``/debug/profile?seconds=N`` on the exposition server (the server
  wires :meth:`SamplingProfiler.debug_handler`);
* ``kccap -profile HOST:PORT -profile-out FILE.collapsed`` (cli.py);
* ``kccap_profiler_samples_total{phase}`` — samples per attributed
  phase (label ``-`` for samples landing outside any phase block);
* a doctor "profiler" line (:func:`profiler_status`).

Hot-path rule: ``KCCAP_PROFILER=0`` (or ``KCCAP_TELEMETRY=0``) pins the
profiler to **zero threads and zero registry calls** — :meth:`start`
returns without spawning anything, pinned by test.  The sampler holds
the GIL only for the ``sys._current_frames()`` snapshot and the fold of
a handful of stacks; at the default 29 Hz the measured overhead on the
solo dispatch path is the bench's ``profile_overhead_p50_ms_{off,on}``
row (≤5% acceptance).  29 is deliberately prime: a sampler phase-locked
to a 10 ms scheduler tick or a 50-per-second batch window would alias,
sampling the same instant of every period.
"""

from __future__ import annotations

import os
import sys
import threading
import time

from kubernetesclustercapacity_tpu.telemetry import phases as _phases

__all__ = [
    "DEFAULT_HZ",
    "SamplingProfiler",
    "enabled",
    "get_profiler",
    "start_profiler",
    "stop_profiler",
    "attribution_counts",
    "phase_counts",
    "dominant_phase",
    "top_frame",
    "profiler_status",
]

#: Default sampling rate (Hz); prime, see module docstring.
DEFAULT_HZ = 29

#: Stack-depth cap per sample and unique-stack cap for the fold table —
#: both bound the profiler's own memory so a pathological workload
#: (deep recursion, codegen'd frames) cannot turn the observer into the
#: leak.  Overflow is counted, never silent.
MAX_DEPTH = 64
MAX_STACKS = 50_000


def enabled() -> bool:
    """Profiler armed?  ``KCCAP_PROFILER=0`` is the dedicated hatch;
    ``KCCAP_TELEMETRY=0`` disables it too (the profiler's metrics and
    attribution both ride the telemetry substrate)."""
    if os.environ.get("KCCAP_PROFILER", "1") == "0":
        return False
    from kubernetesclustercapacity_tpu.telemetry.metrics import (
        enabled as _telemetry_enabled,
    )

    return _telemetry_enabled()


def _env_hz() -> float:
    raw = os.environ.get("KCCAP_PROFILE_HZ", "")
    try:
        hz = float(raw)
    except ValueError:
        return float(DEFAULT_HZ)
    return hz if hz > 0 else float(DEFAULT_HZ)


def _frame_name(frame) -> str:
    """One collapsed-stack element: ``file:function`` with the path
    reduced to its basename (the fold must stay greppable and the
    separator characters must not appear inside an element)."""
    code = frame.f_code
    base = os.path.basename(code.co_filename)
    if base.endswith(".py"):
        base = base[:-3]
    name = f"{base}:{code.co_name}"
    return name.replace(";", ",").replace(" ", "_")


def _fold(frame, attribution) -> str:
    """Fold one thread's stack (innermost ``frame``) into a collapsed
    line, root first, prefixed with synthetic attribution frames
    (``op=...;tenant=...;phase=...``) when the thread is mid-request."""
    names: list[str] = []
    depth = 0
    while frame is not None and depth < MAX_DEPTH:
        names.append(_frame_name(frame))
        frame = frame.f_back
        depth += 1
    names.reverse()
    prefix: list[str] = []
    if attribution is not None:
        op, tenant, phase = attribution
        if op:
            prefix.append(f"op={op}")
        if tenant:
            prefix.append(f"tenant={tenant}")
        if phase:
            prefix.append(f"phase={phase}")
    return ";".join(prefix + names)


class SamplingProfiler:
    """The always-on wall-clock sampler.

    One instance per process (module singleton via :func:`get_profiler`)
    — but the class is self-contained and testable standalone.  All
    mutable state lives under ``self._lock``; the sampler thread writes,
    snapshot/collect readers copy.
    """

    def __init__(self, hz: float | None = None) -> None:
        self._lock = threading.Lock()
        self._hz = float(hz) if hz and hz > 0 else _env_hz()
        self._counts: dict[str, int] = {}
        self._samples = 0
        self._dropped = 0
        self._started_at: float | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._metric = None

    @property
    def hz(self) -> float:
        return self._hz

    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    # -- lifecycle ---------------------------------------------------

    def start(self) -> "SamplingProfiler":
        """Spawn the sampler thread; a no-op (zero threads, zero
        registry calls) when :func:`enabled` says off or when already
        running."""
        if not enabled() or self.running():
            return self
        from kubernetesclustercapacity_tpu.telemetry.metrics import (
            REGISTRY,
        )
        from kubernetesclustercapacity_tpu.utils.threads import (
            supervised,
        )

        self._metric = REGISTRY.counter(
            "kccap_profiler_samples_total",
            "Profiler samples taken, by attributed phase ('-' when the "
            "sampled thread was outside any phase block).",
            ("phase",),
        )
        self._stop.clear()
        with self._lock:
            self._started_at = time.time()
        self._thread = threading.Thread(
            target=supervised(self._loop, name="profiler-sampler"),
            name="kccap-profiler",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
        self._thread = None

    # -- sampling ----------------------------------------------------

    def _loop(self) -> None:
        period = 1.0 / self._hz
        while not self._stop.wait(period):
            self.sample_once()

    def sample_once(self) -> None:
        """Take one sample of every live thread (except the sampler
        itself) and fold it into the table.  Public so tests can drive
        the fold deterministically without a thread."""
        me = threading.get_ident()
        live = _phases.live_snapshot()
        frames = sys._current_frames()
        folded: list[tuple[str, str]] = []
        for ident, frame in frames.items():
            if ident == me:
                continue
            attribution = live.get(ident)
            phase = attribution[2] if attribution else None
            folded.append((_fold(frame, attribution), phase or "-"))
        del frames
        metric = self._metric
        with self._lock:
            self._samples += 1
            for stack, _ in folded:
                if stack in self._counts:
                    self._counts[stack] += 1
                elif len(self._counts) < MAX_STACKS:
                    self._counts[stack] = 1
                else:
                    self._dropped += 1
        if metric is not None:
            for _, phase in folded:
                metric.labels(phase=phase).inc()

    # -- read side ---------------------------------------------------

    def snapshot(self) -> tuple[int, dict[str, int]]:
        """``(samples_so_far, {stack: count})`` — a point-in-time copy."""
        with self._lock:
            return self._samples, dict(self._counts)

    def stats(self) -> dict:
        with self._lock:
            return {
                "hz": self._hz,
                "samples": self._samples,
                "stacks": len(self._counts),
                "dropped_stacks": self._dropped,
                "running": self.running(),
                "uptime_s": (
                    round(time.time() - self._started_at, 1)
                    if self._started_at
                    else 0.0
                ),
            }

    def collect(self, seconds: float) -> str:
        """Profile a window: snapshot, wait ``seconds`` while the
        sampler runs, snapshot again, render the difference as collapsed
        text (most-sampled stack first).  Runs on the CALLER's thread —
        the ``/debug/profile`` handler blocks its own HTTP thread, never
        the sampler."""
        seconds = max(0.0, min(float(seconds), 300.0))
        _, before = self.snapshot()
        if seconds:
            time.sleep(seconds)
        _, after = self.snapshot()
        diff = {
            stack: n - before.get(stack, 0)
            for stack, n in after.items()
            if n - before.get(stack, 0) > 0
        }
        return render_collapsed(diff)

    def debug_handler(self, query: str) -> tuple[str, bytes]:
        """The exposition server's ``/debug/profile`` handler:
        ``query`` is the raw query string; returns ``(content_type,
        body)``.  ``seconds`` defaults to 5."""
        from urllib.parse import parse_qs

        try:
            seconds = float(
                (parse_qs(query).get("seconds") or ["5"])[0]
            )
        except ValueError:
            seconds = 5.0
        if not self.running():
            return (
                "text/plain; charset=utf-8",
                b"# profiler disabled (KCCAP_PROFILER=0 or "
                b"KCCAP_TELEMETRY=0)\n",
            )
        return (
            "text/plain; charset=utf-8",
            self.collect(seconds).encode(),
        )


def render_collapsed(counts: dict[str, int]) -> str:
    """``{stack: count}`` → collapsed-flamegraph text, most-sampled
    first (``flamegraph.pl`` and speedscope both ingest this)."""
    lines = [
        f"{stack} {n}"
        for stack, n in sorted(
            counts.items(), key=lambda kv: (-kv[1], kv[0])
        )
    ]
    return "\n".join(lines) + ("\n" if lines else "")


# -- collapsed-text analysis (shared by cli -profile and bench) --------


def _parse_collapsed(text: str) -> list[tuple[list[str], int]]:
    out: list[tuple[list[str], int]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        stack, _, count = line.rpartition(" ")
        try:
            n = int(count)
        except ValueError:
            continue
        out.append((stack.split(";"), n))
    return out


def attribution_counts(text: str, key: str = "phase") -> dict[str, int]:
    """Samples per attributed ``key`` (``op``/``tenant``/``phase``) in a
    collapsed profile; ``-`` collects the unattributed remainder.  The
    attribution prefixes live in the first three frames of a stack, so
    only those are inspected."""
    prefix = key + "="
    shares: dict[str, int] = {}
    for frames, n in _parse_collapsed(text):
        value = "-"
        for f in frames[:3]:
            if f.startswith(prefix):
                value = f[len(prefix):]
                break
        shares[value] = shares.get(value, 0) + n
    return shares


def phase_counts(text: str) -> dict[str, int]:
    """Samples per attributed phase in a collapsed profile (``-`` =
    unattributed) — the reconciliation surface against the
    ``kccap_phase_seconds`` histogram."""
    return attribution_counts(text, "phase")


def dominant_phase(text: str) -> tuple[str | None, float]:
    """The most-sampled ATTRIBUTED phase and its share of attributed
    samples — ``(None, 0.0)`` when nothing was attributed."""
    shares = phase_counts(text)
    shares.pop("-", None)
    total = sum(shares.values())
    if not total:
        return None, 0.0
    phase = max(shares, key=lambda p: shares[p])
    return phase, shares[phase] / total


def top_frame(text: str, phase: str | None = None) -> str | None:
    """The hottest REAL frame (attribution prefixes skipped), optionally
    restricted to samples attributed to ``phase`` — bench's
    ``serving_top_host_frame`` field."""
    weights: dict[str, int] = {}
    for frames, n in _parse_collapsed(text):
        real = [f for f in frames if "=" not in f.split(":", 1)[0]]
        if phase is not None and f"phase={phase}" not in frames[:3]:
            continue
        if not real:
            continue
        leaf = real[-1]
        weights[leaf] = weights.get(leaf, 0) + n
    if not weights:
        return None
    return max(weights, key=lambda f: weights[f])


# -- module singleton --------------------------------------------------

_singleton_lock = threading.Lock()
_singleton: SamplingProfiler | None = None


def get_profiler() -> SamplingProfiler | None:
    """The process profiler, or ``None`` when never started."""
    return _singleton


def start_profiler(hz: float | None = None) -> SamplingProfiler | None:
    """Start (or return) the process-wide profiler; ``None`` without a
    thread or registry call when :func:`enabled` says off."""
    global _singleton
    if not enabled():
        return None
    with _singleton_lock:
        if _singleton is None:
            _singleton = SamplingProfiler(hz)
    return _singleton.start()


def stop_profiler() -> None:
    global _singleton
    with _singleton_lock:
        prof, _singleton = _singleton, None
    if prof is not None:
        prof.stop()


def profiler_status() -> str:
    """The doctor's "profiler" line (soft when off — an unprofiled
    process is a configuration, not a failure)."""
    if not enabled():
        return (
            "off (KCCAP_PROFILER=0 or KCCAP_TELEMETRY=0) — zero "
            "sampler threads"
        )
    prof = get_profiler()
    if prof is None or not prof.running():
        return (
            f"armed (hz={_env_hz():g}): sampler starts with the "
            "server; /debug/profile on the metrics port"
        )
    st = prof.stats()
    return (
        f"ok: sampling at {st['hz']:g} Hz, {st['samples']} sample(s), "
        f"{st['stacks']} unique stack(s), uptime {st['uptime_s']}s"
    )
