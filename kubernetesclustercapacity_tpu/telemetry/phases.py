"""Per-request latency decomposition: the phase clock.

BENCH_r03 measures ``dispatch_floor_ms`` ≈ 65 of the 72.6 ms exact
single-dispatch p50 — but that floor was one opaque number: nothing
recorded *where* inside a request the time went.  This module is the
decomposition substrate: every answering request is split into a FIXED
phase vocabulary (:data:`PHASES`), each phase a named sub-interval of
the dispatch:

``admission``
    waiting in the admission controller's bounded concurrency queue
    (``service/plane.py``; a request shed at admission records nothing —
    it never became work);
``queue_wait``
    waiting for a compute-inflight slot (``CapacityServer``'s semaphore);
``batch_wait``
    the micro-batch window — the leader's wait for followers, or a
    follower's wait for its leader's combined dispatch
    (``service/batching.py``);
``devcache``
    staging snapshot arrays host→device on a device-cache miss
    (``devcache.py``; a hit records nothing — that is the point of the
    cache);
``compile``
    a dispatch whose kernel label had never dispatched before (joined
    from :mod:`.compilewatch` — the first call per label IS trace +
    XLA/Mosaic compile, and filing it under ``device_exec`` would make
    every cold start look like a runtime regression);
``device_exec``
    the jitted kernel call itself (async launch + any host packing the
    wrapper does before the sync point);
``fetch``
    the device→host materialization — ``np.asarray`` /
    ``block_until_ready`` in ``ops/fit.py`` and ``ops/pallas_fit.py``;
``fetch_overlap``
    the deferred materialization of an async dispatch: the kernel
    returned ``jax.Array`` futures and the request blocked on the
    bytes only at response-build time, so this wait OVERLAPPED the
    next batch's window/dispatch instead of serializing behind it
    (``service/server.py``'s folded sweep path);
``serialize``
    building the wire response (``tolist`` and report rendering).

Threading model: the clock rides a **thread-local** (:func:`activate` /
:func:`restore` / :func:`current`), not a parameter — the phases land
deep inside layers (devcache, the kernel wrappers) whose signatures must
not grow a telemetry argument.  The server's dispatch activates one
clock per request; a micro-batch leader's kernel phases therefore land
on the LEADER's clock while each follower records only its own
``batch_wait`` — per-request attribution stays honest.

Hot-path rule (the package's): with ``KCCAP_TELEMETRY=0``,
:func:`new_clock` returns the process-wide :data:`NULL_CLOCK` singleton
— **zero allocations**, and every instrumentation site gates its
``perf_counter`` pair on the clock's truthiness, so the disabled
dispatch path is byte-identical to the pre-phases one.  Nothing in this
module ever executes inside jitted code.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager

__all__ = [
    "PHASES",
    "PhaseError",
    "PhaseClock",
    "NULL_CLOCK",
    "new_clock",
    "current",
    "activate",
    "restore",
    "live_set",
    "live_clear",
    "live_snapshot",
]

#: The fixed phase vocabulary.  Every phase name recorded anywhere in
#: the package MUST appear here (and in the README's phase table) —
#: pinned by ``tests/test_metric_names.py``'s conformance walk, so the
#: ``kccap_phase_seconds{phase=...}`` label set cannot grow by typo.
PHASES = (
    "admission",
    "queue_wait",
    "batch_wait",
    "devcache",
    "compile",
    "device_exec",
    "fetch",
    "fetch_overlap",
    "serialize",
)

_PHASE_SET = frozenset(PHASES)


class PhaseError(ValueError):
    """A phase name outside the fixed vocabulary."""


class _NullCtx:
    """A reusable no-op context manager (module singleton) — so
    ``with clk.phase(...):`` on the null clock allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_CTX = _NullCtx()


class _NullClock:
    """The disabled clock: a process-wide singleton whose every method
    is a no-op and whose truth value is False, so instrumentation sites
    can gate their ``perf_counter`` pairs with a plain ``if clk:`` —
    zero allocations, zero timing syscalls, zero registry calls."""

    __slots__ = ()

    def __bool__(self) -> bool:
        return False

    def record(self, phase: str, seconds: float) -> None:
        pass

    def move(self, src: str, dst: str) -> None:
        pass

    def items(self):
        return ()

    def counts(self) -> dict:
        return {}

    def to_ms(self) -> dict:
        return {}

    def total_s(self) -> float:
        return 0.0

    def phase(self, name: str):
        return _NULL_CTX

    def live(self, name: str):
        return _NULL_CTX


#: The one instance every disabled dispatch shares (``new_clock`` under
#: ``KCCAP_TELEMETRY=0``, and :func:`current` on a thread with no active
#: clock).
NULL_CLOCK = _NullClock()


# ---------------------------------------------------------------------------
# Live cross-thread attribution: the sampling profiler's join point.
#
# The phase clock accumulates *post hoc* — by the time ``items()`` is
# readable the request is over.  The profiler needs the opposite view:
# "what is thread T doing RIGHT NOW?".  This table publishes, per thread
# ident, the ``(op, tenant, phase)`` triple currently in flight, written
# by the dispatch (``live_set``) and by :meth:`PhaseClock.phase` on
# enter/exit, and read by the sampler thread (``live_snapshot``).  It is
# deliberately tiny: one dict under one lock, entries removed when the
# request finishes, and NEVER touched on the ``KCCAP_TELEMETRY=0`` path
# (every writer is gated on clock truthiness, same as the clocks
# themselves).
# ---------------------------------------------------------------------------

_live_lock = threading.Lock()
_live: dict[int, tuple] = {}


def live_set(op=None, tenant=None) -> None:
    """Publish ``(op, tenant)`` as the calling thread's in-flight work
    (phase starts unset; :meth:`PhaseClock.phase` fills it)."""
    with _live_lock:
        _live[threading.get_ident()] = (op, tenant, None)


def live_clear() -> None:
    """Retire the calling thread's attribution entry (request done)."""
    with _live_lock:
        _live.pop(threading.get_ident(), None)


def live_snapshot() -> dict:
    """A point-in-time copy ``{thread_ident: (op, tenant, phase)}`` —
    the sampler's read side."""
    with _live_lock:
        return dict(_live)


def _live_enter_phase(ident: int, name: str):
    """Mark ``name`` as ``ident``'s current phase; returns the previous
    entry (or ``None``) for :func:`_live_exit_phase`."""
    with _live_lock:
        prev = _live.get(ident)
        if prev is None:
            _live[ident] = (None, None, name)
        else:
            _live[ident] = (prev[0], prev[1], name)
        return prev


def _live_exit_phase(ident: int, prev) -> None:
    """Undo :func:`_live_enter_phase` (phases nest — restore the outer
    entry, or remove the one we created)."""
    with _live_lock:
        if prev is None:
            cur = _live.get(ident)
            if cur is not None and cur[0] is None and cur[1] is None:
                del _live[ident]
        else:
            _live[ident] = prev


class PhaseClock:
    """Per-request phase accumulator, safe for concurrent recorders.

    One clock is one request's decomposition: ``record`` adds a timed
    sub-interval to a phase (phases may be recorded more than once —
    e.g. two devcache stagings — and accumulate), ``move`` reattributes
    one phase's whole accumulation to another (the compile join:
    :func:`~.compilewatch.observe_dispatch` only classifies a dispatch
    *after* it ran, so ``device_exec``/``fetch`` recorded during a
    first-call dispatch move into ``compile``).  The lock exists because
    a request's phases can be recorded from more than one thread (the
    micro-batch leader's dispatch callback), and because the concurrency
    hammer in ``tests/test_phases.py`` pins exact counts.
    """

    __slots__ = ("_lock", "_acc", "_counts")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._acc: dict[str, float] = {}
        self._counts: dict[str, int] = {}

    def __bool__(self) -> bool:
        return True

    def record(self, phase: str, seconds: float) -> None:
        """Add one timed sub-interval to ``phase`` (vocabulary-checked)."""
        if phase not in _PHASE_SET:
            raise PhaseError(
                f"unknown phase {phase!r} (vocabulary: {PHASES})"
            )
        seconds = float(seconds)
        with self._lock:
            self._acc[phase] = self._acc.get(phase, 0.0) + seconds
            self._counts[phase] = self._counts.get(phase, 0) + 1

    def move(self, src: str, dst: str) -> None:
        """Reattribute all of ``src``'s accumulation to ``dst``."""
        for p in (src, dst):
            if p not in _PHASE_SET:
                raise PhaseError(
                    f"unknown phase {p!r} (vocabulary: {PHASES})"
                )
        with self._lock:
            s = self._acc.pop(src, None)
            if s is None:
                return
            c = self._counts.pop(src, 0)
            self._acc[dst] = self._acc.get(dst, 0.0) + s
            self._counts[dst] = self._counts.get(dst, 0) + c

    @contextmanager
    def phase(self, name: str):
        """Time a block into ``name`` (host-side convenience).  Also
        publishes ``name`` to the live attribution table so a profiler
        sample landing inside the block carries the phase."""
        if name not in _PHASE_SET:
            raise PhaseError(
                f"unknown phase {name!r} (vocabulary: {PHASES})"
            )
        ident = threading.get_ident()
        prev = _live_enter_phase(ident, name)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            _live_exit_phase(ident, prev)
            self.record(name, dt)

    @contextmanager
    def live(self, name: str):
        """Publish ``name`` as the calling thread's live phase for the
        block WITHOUT timing or recording anything — for sites that
        measure with explicit ``perf_counter`` pairs and classify the
        window post hoc (the kernel wrappers' compile/device_exec
        split), so a profiler sample landing inside still carries a
        phase.  The accounting stays with the site's own ``record``
        calls; this is attribution only."""
        if name not in _PHASE_SET:
            raise PhaseError(
                f"unknown phase {name!r} (vocabulary: {PHASES})"
            )
        ident = threading.get_ident()
        prev = _live_enter_phase(ident, name)
        try:
            yield
        finally:
            _live_exit_phase(ident, prev)

    def items(self) -> list[tuple[str, float]]:
        """``(phase, accumulated_seconds)`` pairs in vocabulary order
        (only phases actually recorded — an absent phase never emits a
        zero sample into the histograms)."""
        with self._lock:
            acc = dict(self._acc)
        return [(p, acc[p]) for p in PHASES if p in acc]

    def counts(self) -> dict[str, int]:
        """Recorded-interval count per phase (hammer-test surface)."""
        with self._lock:
            return dict(self._counts)

    def to_ms(self) -> dict[str, float]:
        """``{phase: milliseconds}`` rounded to µs — the compact form
        the flight recorder carries per record."""
        return {p: round(s * 1e3, 3) for p, s in self.items()}

    def total_s(self) -> float:
        """Sum of all recorded phases (reconciliation surface)."""
        with self._lock:
            return sum(self._acc.values())


def new_clock():
    """A fresh :class:`PhaseClock` — or :data:`NULL_CLOCK` when
    telemetry is off (``KCCAP_TELEMETRY=0`` means zero phase-clock
    allocations on the dispatch path, pinned by test)."""
    from kubernetesclustercapacity_tpu.telemetry.metrics import enabled

    if not enabled():
        return NULL_CLOCK
    return PhaseClock()


_tls = threading.local()


def current():
    """The calling thread's active clock (``NULL_CLOCK`` when none) —
    what the deep instrumentation sites (devcache, batching, the kernel
    wrappers) consult without a threading-through parameter."""
    return getattr(_tls, "clock", None) or NULL_CLOCK


def activate(clock):
    """Install ``clock`` as this thread's active clock; returns the
    previous one for :func:`restore` (dispatchers nest — a reload op's
    internal work must not leak onto a stale clock)."""
    prev = getattr(_tls, "clock", None)
    _tls.clock = clock
    return prev


def restore(prev) -> None:
    """Undo :func:`activate` (pass its return value)."""
    _tls.clock = prev
