"""SLO objectives + multi-window error-budget burn rates for the service.

The serving stack watches capacity (the PR-5 timeline) and correctness
(the PR-6 shadow oracle); this module watches the service's *own*
latency and availability — the first thing a fleet serving real traffic
needs alarmed.  The machinery is the SRE-workbook multi-window burn
rate:

* an **objective** defines what "bad" means — a latency objective
  (``p99 < 80ms``: a request slower than the threshold spends budget)
  or an availability objective (``99.9%``: an errored or shed request
  spends budget);
* the **error budget** is the allowed bad fraction (``1 − 0.99`` for a
  p99 objective, ``1 − target`` for availability);
* the **burn rate** over a window is ``bad_fraction / budget`` — 1.0
  burns the budget exactly at the sustainable rate, 14 burns a 30-day
  budget in ~2 days;
* an SLO is **fast-burning** when the burn rate exceeds its threshold
  over BOTH the short and the long window: the long window proves the
  burn is significant, the short window proves it is still happening
  (so recovery un-pages promptly).

State comes from rolling snapshots of the server's OWN registry
counters (``kccap_request_latency_seconds`` buckets for latency,
``kccap_requests_total`` / ``kccap_request_errors_total`` /
``kccap_deadline_shed_total`` for availability) — no second measurement
path that could disagree with the scrape.  Each evaluation appends one
cumulative sample per SLO and differences it against the sample at the
window start; the window math itself (:func:`burn_rate`) is a pure
function pinned against a numpy oracle in ``tests/test_slo.py``.

Fast burn drives the existing :class:`~..timeline.alerts.WatchAlert`
ok→breached→recovered machine, ``kccap_slo_*`` gauges, ``/healthz``
(503 while fast-burning), the ``slo`` protocol op /
``kccap -slo-status``, the doctor's "latency & SLO" line, and an
optional JSONL transition log.  ``KCCAP_TELEMETRY=0`` keeps the whole
module registry-silent, same contract as every telemetry layer.

The ``-slo`` file rides the watchlist flag grammar (YAML when PyYAML
exists, strict JSON otherwise)::

    slos:
      - name: sweep-latency
        op: sweep                 # omit to cover every op
        latency: "p99 < 100ms"
        short_window_s: 60        # optional (defaults below)
        long_window_s: 600
        fast_burn: 14
      - name: availability
        availability: "99.9%"     # or 0.999
"""

from __future__ import annotations

import json
import re
import threading
import time
from dataclasses import dataclass

from kubernetesclustercapacity_tpu.telemetry.metrics import (
    enabled as _telemetry_enabled,
)
from kubernetesclustercapacity_tpu.timeline.alerts import (
    ALERT_BREACHED,
    WatchAlert,
)

__all__ = [
    "SLOError",
    "SLOSpec",
    "SLOMonitor",
    "parse_slos",
    "load_slos",
    "burn_rate",
    "estimate_quantile",
]

#: Multi-window defaults: the workbook's page-worthy pairing scaled to a
#: service whose incidents are minutes, not days.
DEFAULT_SHORT_WINDOW_S = 60.0
DEFAULT_LONG_WINDOW_S = 600.0
DEFAULT_FAST_BURN = 14.0

_LATENCY_RE = re.compile(
    r"^\s*p(\d+(?:\.\d+)?)\s*<\s*(\d+(?:\.\d+)?)\s*(ms|s)\s*$"
)

_ENTRY_KEYS = frozenset(
    {
        "name", "op", "tenant", "latency", "availability",
        "short_window_s", "long_window_s", "fast_burn",
    }
)


class SLOError(ValueError):
    """Malformed SLO file/entry (bad grammar, bad numbers, dupes)."""


@dataclass(frozen=True)
class SLOSpec:
    """One objective: what counts as bad, and when burning it pages."""

    name: str
    kind: str  # "latency" | "availability"
    op: str | None = None  # None = every op
    #: Latency only: evaluate over ONE tenant's requests (the server's
    #: kccap_tenant_request_latency_seconds family) instead of per op.
    #: Use the map's names — unmapped traffic folds to "other".
    tenant: str | None = None
    quantile: float | None = None  # latency: 0.99 for p99
    threshold_s: float | None = None  # latency objective bound
    target: float | None = None  # availability: 0.999
    short_window_s: float = DEFAULT_SHORT_WINDOW_S
    long_window_s: float = DEFAULT_LONG_WINDOW_S
    fast_burn: float = DEFAULT_FAST_BURN

    @property
    def budget(self) -> float:
        """The allowed bad fraction (the error budget's size)."""
        if self.kind == "latency":
            return 1.0 - self.quantile
        return 1.0 - self.target

    @property
    def objective(self) -> str:
        """Human rendering (reports / doctor / wire)."""
        if self.kind == "latency":
            q = self.quantile * 100
            q_str = f"{q:g}"
            return f"p{q_str} < {self.threshold_s * 1e3:g}ms"
        return f"availability >= {self.target * 100:g}%"

    def to_wire(self) -> dict:
        return {
            "name": self.name,
            "kind": self.kind,
            "op": self.op,
            # Present only when set: tenantless specs keep their exact
            # pre-tenancy wire shape.
            **({"tenant": self.tenant} if self.tenant is not None else {}),
            "objective": self.objective,
            "budget": self.budget,
            "short_window_s": self.short_window_s,
            "long_window_s": self.long_window_s,
            "fast_burn": self.fast_burn,
        }


def _parse_fraction(name: str, field: str, v) -> float:
    """``0.999`` or ``"99.9%"`` → the fraction in (0, 1)."""
    if isinstance(v, str):
        s = v.strip()
        if s.endswith("%"):
            try:
                v = float(s[:-1]) / 100.0
            except ValueError as e:
                raise SLOError(
                    f"slo {name!r}: bad {field} {s!r}"
                ) from e
        else:
            try:
                v = float(s)
            except ValueError as e:
                raise SLOError(
                    f"slo {name!r}: bad {field} {s!r}"
                ) from e
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise SLOError(f"slo {name!r}: {field} must be a number or 'NN%'")
    v = float(v)
    if not 0.0 < v < 1.0:
        raise SLOError(
            f"slo {name!r}: {field} must be strictly between 0 and 1 "
            f"(got {v})"
        )
    return v


def _parse_entry(i: int, entry) -> SLOSpec:
    if not isinstance(entry, dict):
        raise SLOError(f"slo #{i}: expected a mapping, got {entry!r}")
    name = entry.get("name")
    if not isinstance(name, str) or not name:
        raise SLOError(f"slo #{i}: 'name' must be a non-empty string")
    unknown = set(entry) - _ENTRY_KEYS
    if unknown:
        raise SLOError(
            f"slo {name!r}: unknown field(s) {sorted(unknown)} "
            f"(want a subset of {sorted(_ENTRY_KEYS)})"
        )
    op = entry.get("op")
    if op is not None and (not isinstance(op, str) or not op):
        raise SLOError(f"slo {name!r}: 'op' must be a non-empty string")
    tenant = entry.get("tenant")
    if tenant is not None and (not isinstance(tenant, str) or not tenant):
        raise SLOError(
            f"slo {name!r}: 'tenant' must be a non-empty string"
        )
    if tenant is not None and op is not None:
        # Per-tenant latency reads the tenant-labeled family, which has
        # no op dimension — the combination would silently mean "ignore
        # op", so it errors instead.
        raise SLOError(
            f"slo {name!r}: 'tenant' and 'op' are mutually exclusive"
        )
    has_latency = "latency" in entry
    has_avail = "availability" in entry
    if has_latency == has_avail:
        raise SLOError(
            f"slo {name!r}: exactly one of 'latency' or 'availability' "
            "is required"
        )
    windows = {}
    for field, default in (
        ("short_window_s", DEFAULT_SHORT_WINDOW_S),
        ("long_window_s", DEFAULT_LONG_WINDOW_S),
        ("fast_burn", DEFAULT_FAST_BURN),
    ):
        v = entry.get(field, default)
        if isinstance(v, bool) or not isinstance(v, (int, float)) or v <= 0:
            raise SLOError(
                f"slo {name!r}: {field} must be a positive number"
            )
        windows[field] = float(v)
    if windows["short_window_s"] >= windows["long_window_s"]:
        raise SLOError(
            f"slo {name!r}: short_window_s must be < long_window_s"
        )
    if has_latency:
        spec_str = entry["latency"]
        if not isinstance(spec_str, str):
            raise SLOError(
                f"slo {name!r}: latency objective must be a string like "
                "'p99 < 80ms'"
            )
        m = _LATENCY_RE.match(spec_str)
        if m is None:
            raise SLOError(
                f"slo {name!r}: cannot parse latency objective "
                f"{spec_str!r} (want e.g. 'p99 < 80ms')"
            )
        q = float(m.group(1)) / 100.0
        if not 0.0 < q < 1.0:
            raise SLOError(
                f"slo {name!r}: latency quantile must be in (p0, p100)"
            )
        bound = float(m.group(2))
        threshold_s = bound / 1e3 if m.group(3) == "ms" else bound
        if threshold_s <= 0:
            raise SLOError(f"slo {name!r}: latency bound must be > 0")
        return SLOSpec(
            name=name, kind="latency", op=op, tenant=tenant, quantile=q,
            threshold_s=threshold_s, **windows,
        )
    if tenant is not None:
        # Availability is op-scoped (errors carry an op, not a tenant);
        # per-tenant availability would need a tenant-labeled error
        # family this server does not keep (bounded cardinality).
        raise SLOError(
            f"slo {name!r}: 'tenant' is only valid on latency objectives"
        )
    target = _parse_fraction(name, "availability", entry["availability"])
    return SLOSpec(name=name, kind="availability", op=op, target=target,
                   **windows)


def parse_slos(data) -> tuple[SLOSpec, ...]:
    """Parsed document (``{"slos": [...]}`` or a bare list) → specs."""
    if isinstance(data, dict):
        entries = data.get("slos")
        extra = set(data) - {"slos"}
        if extra:
            raise SLOError(f"unknown top-level field(s) {sorted(extra)}")
    else:
        entries = data
    if not isinstance(entries, list) or not entries:
        raise SLOError(
            "slo file wants a non-empty 'slos' list (or a bare list)"
        )
    specs = tuple(_parse_entry(i, e) for i, e in enumerate(entries))
    names = [s.name for s in specs]
    if len(set(names)) != len(names):
        dupes = sorted({n for n in names if names.count(n) > 1})
        raise SLOError(f"duplicate slo name(s): {dupes}")
    return specs


def load_slos(path: str) -> tuple[SLOSpec, ...]:
    """Load ``path`` — YAML when PyYAML is present, else strict JSON
    (the watchlist loader's exact gating)."""
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    try:
        import yaml  # type: ignore[import-untyped]

        data = yaml.safe_load(text)
    except ImportError:
        try:
            data = json.loads(text)
        except ValueError as e:
            raise SLOError(
                f"{path}: not valid JSON (and PyYAML is unavailable): {e}"
            ) from e
    except Exception as e:  # yaml.YAMLError — malformed document
        raise SLOError(f"{path}: cannot parse: {e}") from e
    return parse_slos(data)


# -- the window math (pure; numpy-oracle-pinned) ---------------------------
def burn_rate(samples, *, now: float, window_s: float, budget: float):
    """Burn rate over ``[now − window_s, now]`` from cumulative samples.

    ``samples`` is an ordered iterable of ``(ts, total, bad)`` with
    ``total``/``bad`` CUMULATIVE counts (monotone non-decreasing, ts
    ascending).  The window's baseline is the newest sample at or before
    the window start — or, when history is shorter than the window, the
    oldest sample available (a partial window is honest about the
    history it has; refusing to alert until a full long window elapsed
    would blind the first ten minutes of every deploy).  The head is the
    newest sample at or before ``now``.

    Returns ``bad_fraction / budget`` for the delta between baseline and
    head, ``0.0`` when the window saw no traffic, or ``None`` when there
    are fewer than two distinct samples to difference.
    """
    if budget <= 0:
        raise SLOError(f"budget must be > 0, got {budget}")
    head = None
    baseline = None
    first_in_history = None
    start = now - window_s
    for s in samples:
        ts = s[0]
        if ts > now:
            break
        if first_in_history is None:
            first_in_history = s
        if ts <= start:
            baseline = s
        head = s
    if baseline is None:
        baseline = first_in_history
    if head is None or baseline is None or head is baseline:
        return None
    d_total = head[1] - baseline[1]
    d_bad = head[2] - baseline[2]
    if d_total <= 0:
        return 0.0
    return (d_bad / d_total) / budget


def estimate_quantile(buckets: dict, count: int, q: float):
    """Quantile estimate from a cumulative bucket dict (the histogram
    snapshot's ``{le_str: cumulative}`` form), linearly interpolated
    inside the winning bucket.  ``None`` when the histogram is empty.
    The doctor's latency line and the reports use this — an estimate
    bounded by bucket resolution, which is why kernel/phase histograms
    carry the sub-millisecond ladder."""
    if count <= 0:
        return None
    rank = q * count
    lo = 0.0
    prev_cum = 0
    last_finite = 0.0
    for le_str, cum in buckets.items():
        if le_str == "+Inf":
            break
        le = float(le_str)
        if cum >= rank and cum > prev_cum:
            frac = (rank - prev_cum) / (cum - prev_cum)
            return lo + (le - lo) * max(0.0, min(1.0, frac))
        lo = le
        prev_cum = cum
        last_finite = le
    return last_finite  # the quantile lives in the +Inf bucket


def _hist_bad_count(child, threshold_s: float) -> int:
    """Observations provably above ``threshold_s`` in a histogram child:
    ``count − cumulative(first boundary ≥ threshold)``.  Thresholds
    should sit on bucket boundaries (the sub-ms ladder makes that easy);
    otherwise the next boundary up is used, undercounting within one
    bucket — conservative, never a false page."""
    snap = child.snapshot()
    count = snap["count"]
    cum_at = None
    for le_str, cum in snap["buckets"].items():
        if le_str == "+Inf":
            continue
        if float(le_str) >= threshold_s - 1e-12:
            cum_at = cum
            break
    if cum_at is None:
        # Threshold beyond the last finite boundary: everything in the
        # +Inf region violates it (a wedged request must spend budget).
        last = 0
        for le_str, cum in snap["buckets"].items():
            if le_str != "+Inf":
                last = cum
        cum_at = last
    return int(count - cum_at)


def registry_source(registry):
    """The default counter source: reads (total, bad) cumulative counts
    per spec straight from the server's own request metrics, so the SLO
    verdict and the scrape can never disagree.  Families are created
    idempotently with the server's exact declarations."""
    lat = registry.histogram(
        "kccap_request_latency_seconds",
        "End-to-end dispatch latency, by op.",
        ("op",),
    )
    req = registry.counter(
        "kccap_requests_total", "Requests dispatched, by op.", ("op",)
    )
    err = registry.counter(
        "kccap_request_errors_total",
        "Requests that raised, by op and exception type.",
        ("op", "error"),
    )
    shed = registry.counter(
        "kccap_deadline_shed_total",
        "Requests shed because their deadline had already expired.",
    )

    def read(spec: SLOSpec) -> tuple[int, int]:
        if spec.kind == "latency":
            fam = lat
            if spec.tenant is not None:
                # Created idempotently with the server's exact
                # declaration; lazily, so tenantless deployments never
                # grow the family in their registry snapshot.
                fam = registry.histogram(
                    "kccap_tenant_request_latency_seconds",
                    "End-to-end dispatch latency, by tenant (bounded "
                    "cardinality; feeds per-tenant SLO specs).",
                    ("tenant",),
                )
            total = bad = 0
            for key, child in fam._items():
                if spec.tenant is not None:
                    if key[0] != spec.tenant:
                        continue
                elif spec.op is not None and key[0] != spec.op:
                    continue
                total += child.count
                bad += _hist_bad_count(child, spec.threshold_s)
            return total, bad
        total = 0
        for key, child in req._items():
            if spec.op is not None and key[0] != spec.op:
                continue
            total += int(child.value)
        bad = 0
        for key, child in err._items():
            if spec.op is not None and key[0] != spec.op:
                continue
            bad += int(child.value)
        # Shed requests are unavailability too (the caller got no
        # answer); the shed counter is op-less, so it spends every
        # availability objective's budget.
        bad += int(shed.labels().value)
        return total, bad

    return read


class SLOMonitor:
    """Rolling burn-rate evaluation + the ok→breached→recovered machine.

    ``source`` is a callable ``spec → (total, bad)`` cumulative counts
    (default: :func:`registry_source` over ``registry``).  ``evaluate``
    appends one sample per spec and recomputes both windows; it is
    called by the ``slo`` op and ``/healthz`` on read (state is always
    fresh when queried) and optionally by :meth:`start`'s background
    thread (gauges stay fresh for scrapers that never query).

    Telemetry: ``kccap_slo_burn_rate{slo,window}``,
    ``kccap_slo_alert_state{slo}`` (0 ok / 1 recovered / 2 breached),
    ``kccap_slo_breaches_total{slo}`` — registered only when a registry
    is given AND telemetry is enabled (``KCCAP_TELEMETRY=0`` = zero
    registry calls, pinned by test).  ``log`` (path or
    :class:`~.tracing.TraceLog`) receives one JSONL line per alert
    transition.
    """

    def __init__(
        self,
        specs,
        *,
        registry=None,
        source=None,
        log=None,
        time_fn=time.time,
    ) -> None:
        from kubernetesclustercapacity_tpu.telemetry.tracing import TraceLog

        specs = tuple(specs)
        if not specs:
            raise SLOError("SLOMonitor wants at least one SLOSpec")
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise SLOError(f"duplicate slo names: {names}")
        if source is None:
            if registry is None:
                raise SLOError(
                    "SLOMonitor needs a registry (for the default "
                    "counter source) or an explicit source"
                )
            source = registry_source(registry)
        self.specs = specs
        self._source = source
        self._time = time_fn
        self._lock = threading.Lock()
        # Ring depth: enough samples to always bracket the long window
        # at the fastest plausible evaluation cadence (~1/s) — bounded,
        # and the window math only reads the bracketing two anyway.
        self._samples = {
            s.name: [] for s in specs
        }
        self._max_samples = {
            s.name: max(int(s.long_window_s) * 2 + 16, 64) for s in specs
        }
        # min_replicas=1 re-uses the timeline's machine verbatim: the
        # monitor feeds 0 while fast-burning and 1 while not, so
        # "capacity below threshold" IS "budget burning too fast".
        self._alerts = {s.name: WatchAlert(s.name, 1) for s in specs}
        self._burns: dict[str, dict] = {
            s.name: {"short": None, "long": None} for s in specs
        }
        self._evals = 0
        self._log = TraceLog(log) if isinstance(log, str) else log
        self._m = None
        if registry is not None and _telemetry_enabled():
            self._m = {
                "burn": registry.gauge(
                    "kccap_slo_burn_rate",
                    "Error-budget burn rate (1.0 = exactly sustainable), "
                    "by SLO and window.",
                    ("slo", "window"),
                ),
                "state": registry.gauge(
                    "kccap_slo_alert_state",
                    "SLO alert state (0=ok, 1=recovered, 2=breached).",
                    ("slo",),
                ),
                "breaches": registry.counter(
                    "kccap_slo_breaches_total",
                    "Fast-burn breaches entered, by SLO.",
                    ("slo",),
                ),
            }
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    # -- evaluation --------------------------------------------------------
    def evaluate(self, now: float | None = None) -> dict:
        """Sample every objective's counters and advance the machine.

        Returns ``{name: {"short_burn", "long_burn", "fast_burning",
        "state", "transition"}}`` for this evaluation.  Deterministic
        under an injected ``now`` (tests drive synthetic series through
        an injected ``source``)."""
        now = self._time() if now is None else float(now)
        out: dict[str, dict] = {}
        with self._lock:
            self._evals += 1
            seq = self._evals
            for spec in self.specs:
                total, bad = self._source(spec)
                ring = self._samples[spec.name]
                ring.append((now, int(total), int(bad)))
                if len(ring) > self._max_samples[spec.name]:
                    del ring[: len(ring) - self._max_samples[spec.name]]
                short = burn_rate(
                    ring, now=now, window_s=spec.short_window_s,
                    budget=spec.budget,
                )
                long_ = burn_rate(
                    ring, now=now, window_s=spec.long_window_s,
                    budget=spec.budget,
                )
                self._burns[spec.name] = {"short": short, "long": long_}
                fast = (
                    short is not None
                    and long_ is not None
                    and short > spec.fast_burn
                    and long_ > spec.fast_burn
                )
                alert = self._alerts[spec.name]
                transition = alert.update(0 if fast else 1, seq)
                if transition is not None:
                    self._append_log(spec, transition, short, long_, now)
                self._publish_metrics(spec, short, long_, alert)
                out[spec.name] = {
                    "short_burn": short,
                    "long_burn": long_,
                    "fast_burning": fast,
                    "state": alert.state,
                    "transition": transition,
                }
        return out

    def _publish_metrics(self, spec, short, long_, alert) -> None:
        if self._m is None or not _telemetry_enabled():
            return
        m = self._m
        for window, value in (("short", short), ("long", long_)):
            m["burn"].labels(slo=spec.name, window=window).set(
                value if value is not None else 0.0
            )
        m["state"].labels(slo=spec.name).set(alert.state_code)
        if alert.breaches:
            c = m["breaches"].labels(slo=spec.name)
            c.inc(alert.breaches - c.value)

    def _append_log(self, spec, transition, short, long_, now) -> None:
        if self._log is None:
            return
        try:
            self._log.record(
                kind="slo_alert",
                ts=now,
                slo=spec.name,
                objective=spec.objective,
                transition=transition,
                short_burn=short,
                long_burn=long_,
                fast_burn=spec.fast_burn,
            )
        except Exception:  # noqa: BLE001 - logging must not fail an eval
            pass

    # -- read surfaces -----------------------------------------------------
    @property
    def fast_burning(self) -> bool:
        """True while ANY objective's alert is breached — the
        ``/healthz`` 503 condition."""
        with self._lock:
            return any(
                a.state == ALERT_BREACHED for a in self._alerts.values()
            )

    def status(self) -> dict:
        """Per-SLO state (``slo`` op body, ``kccap -slo-status``)."""
        with self._lock:
            out = {}
            for spec in self.specs:
                alert = self._alerts[spec.name]
                burns = self._burns[spec.name]
                ring = self._samples[spec.name]
                head = ring[-1] if ring else None
                out[spec.name] = {
                    "objective": spec.objective,
                    "op": spec.op,
                    "state": alert.state,
                    "breaches": alert.breaches,
                    "recoveries": alert.recoveries,
                    "short_burn": burns["short"],
                    "long_burn": burns["long"],
                    "fast_burn": spec.fast_burn,
                    "fast_burning": alert.state == ALERT_BREACHED,
                    "total": head[1] if head else 0,
                    "bad": head[2] if head else 0,
                }
            return out

    def wire(self) -> dict:
        """The ``slo`` op's response body."""
        with self._lock:
            # _evals is incremented under the lock by evaluate(); read
            # it the same way so the wire view is a consistent count.
            evals = self._evals
        return {
            "enabled": True,
            "specs": [s.to_wire() for s in self.specs],
            "status": self.status(),
            "fast_burning": self.fast_burning,
            "evaluations": evals,
        }

    def stats(self) -> dict:
        """Compact health view (``/healthz``, doctor)."""
        with self._lock:
            states = {n: a.state for n, a in self._alerts.items()}
            evals = self._evals
        return {
            "slos": [s.name for s in self.specs],
            "states": states,
            "breached": sorted(
                n for n, s in states.items() if s == ALERT_BREACHED
            ),
            "evaluations": evals,
        }

    # -- lifecycle ---------------------------------------------------------
    def start(self, interval_s: float = 5.0) -> "SLOMonitor":
        """Background evaluation so gauges/healthz stay fresh without a
        querier (the server's main starts this; tests call
        :meth:`evaluate` directly)."""
        if interval_s <= 0:
            raise SLOError("interval_s must be > 0")

        def loop() -> None:
            while not self._stop.wait(interval_s):
                try:
                    self.evaluate()
                except Exception:  # noqa: BLE001 - monitor must outlive blips
                    pass

        from kubernetesclustercapacity_tpu.utils.threads import supervised

        self._thread = threading.Thread(
            target=supervised(loop, name="kccap-slo-eval"),
            name="kccap-slo-eval",
            daemon=True,
        )
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._log is not None:
            self._log.close()
