"""Request tracing: trace/span IDs through the protocol, JSONL span log.

A trace ID rides any service request as a ``trace_id`` field — threaded
through the protocol envelope exactly the way ``deadline`` already is —
so one client-side ID stitches together the client's attempt, the
server's dispatch span, and (later) any fan-out.  Span timings feed a
registry histogram; an optional :class:`TraceLog` appends one JSON line
per finished span, the grep-able forensic record (who asked, which op,
how long, what failed) a latency histogram cannot carry.

IDs follow the W3C-traceparent shape (hex, 16-byte trace / 8-byte span)
without the header framing: this stack speaks framed JSON, not HTTP,
and the hex form converts losslessly if a gateway ever bridges the two.
"""

from __future__ import annotations

import json
import os
import threading
import time

__all__ = ["new_trace_id", "new_span_id", "TraceLog", "Span"]


def new_trace_id() -> str:
    """A fresh 16-byte hex trace ID (W3C trace-id shaped)."""
    return os.urandom(16).hex()


def new_span_id() -> str:
    """A fresh 8-byte hex span ID."""
    return os.urandom(8).hex()


class TraceLog:
    """Append-only JSONL span log, safe for many threads.

    One ``record(**fields)`` is one line, written and flushed under a
    lock so concurrent dispatch threads can never interleave bytes.
    Opened lazily (first record) so constructing a server with a trace
    path that never traces costs nothing, and close() is idempotent.

    ``max_bytes`` caps on-disk growth with one-deep rotation: when an
    append pushes the file past the cap, it is renamed to ``PATH.1``
    (clobbering any previous ``.1``) and a fresh file starts — a
    long-lived server cannot fill the disk, and the most recent ~2x
    ``max_bytes`` of spans always survive.  ``0`` (the default) keeps
    the historical unbounded behavior.

    The first lazy open registers an ``atexit`` close for this log, so
    a short-lived ``kccap`` run that never reaches an explicit
    ``close()`` (early ``sys.exit``, an embedder that forgot the
    context manager) still flushes and closes its final spans at
    interpreter shutdown — the last span of a one-shot CLI invocation
    is precisely the one a trace pipeline must not lose.
    """

    def __init__(self, path: str, *, max_bytes: int = 0) -> None:
        if max_bytes < 0:
            raise ValueError(f"max_bytes must be >= 0, got {max_bytes}")
        self.path = path
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._fh = None
        self._closed = False
        self._atexit_registered = False

    def record(self, **fields) -> None:
        line = json.dumps(fields, sort_keys=True)
        with self._lock:
            if self._closed:
                return
            if self._fh is None:
                self._fh = open(self.path, "a", encoding="utf-8")
                if not self._atexit_registered:
                    import atexit

                    atexit.register(self.close)
                    self._atexit_registered = True
            self._fh.write(line + "\n")
            self._fh.flush()
            if self.max_bytes and self._fh.tell() > self.max_bytes:
                self._rotate_locked()

    def _rotate_locked(self) -> None:
        """Rename the full log to ``.1`` and reopen fresh (lock held).

        The record that tripped the cap stays in the rotated file — a
        span is never torn across the boundary, and a single oversized
        span rotates rather than wedging the log.
        """
        try:
            self._fh.close()
        finally:
            self._fh = None
        os.replace(self.path, self.path + ".1")
        self._fh = open(self.path, "a", encoding="utf-8")

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._fh is not None:
                try:
                    self._fh.close()
                finally:
                    self._fh = None

    def __enter__(self) -> "TraceLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class Span:
    """One timed operation: context manager feeding histogram + log.

    ``histogram`` is an optional pre-labeled histogram *child* (the
    caller picks the labels — e.g. ``latency.labels(op="sweep")``);
    ``trace_log`` an optional :class:`TraceLog`.  An exception leaving
    the block marks the span ``status="error"`` with the exception type
    and propagates unchanged — tracing observes failures, never eats
    them.  ``extra`` fields ride the log line verbatim.
    """

    def __init__(
        self,
        op: str,
        *,
        trace_id: str | None = None,
        parent_span_id: str | None = None,
        histogram=None,
        trace_log: TraceLog | None = None,
        extra: dict | None = None,
    ) -> None:
        self.op = op
        self.trace_id = trace_id or new_trace_id()
        self.span_id = new_span_id()
        self.parent_span_id = parent_span_id
        self.duration_s: float | None = None
        self.error: str | None = None
        self._histogram = histogram
        self._trace_log = trace_log
        self._extra = dict(extra or {})
        self._t0: float | None = None
        self._wall0: float | None = None

    def __enter__(self) -> "Span":
        self._t0 = time.perf_counter()
        self._wall0 = time.time()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        # duration_s is MONOTONIC (perf_counter): a wall-clock step
        # mid-span (NTP slew, operator date change) can never yield a
        # negative duration.  The wall clock appears only as the
        # ``start_ts``/``ts`` anchors — which IS where a step shows up,
        # so the trace analyzer flags spans whose recorded duration is
        # negative (foreign/legacy writers) as ``clock_skew`` instead of
        # feeding them to the critical path.
        self.duration_s = time.perf_counter() - (self._t0 or 0.0)
        if exc is not None:
            self.error = f"{exc_type.__name__}: {exc}"
        if self._histogram is not None:
            self._histogram.observe(self.duration_s)
        if self._trace_log is not None:
            rec = {
                "ts": time.time(),
                "start_ts": getattr(self, "_wall0", None),
                "trace_id": self.trace_id,
                "span_id": self.span_id,
                "op": self.op,
                "duration_ms": round(self.duration_s * 1e3, 3),
                "status": "error" if self.error else "ok",
                **self._extra,
            }
            if self.parent_span_id:
                rec["parent_span_id"] = self.parent_span_id
            if self.error:
                rec["error"] = self.error
            self._trace_log.record(**rec)
        # Exceptions propagate (return None/False).
