"""Telemetry: metrics registry, Prometheus exposition, request tracing.

The measurement substrate for the production-scale service (ROADMAP
north-star): every layer of the capacity stack — server dispatch, client
transport, follower sync loop, fused-kernel path — records counters,
gauges and latency histograms into a :class:`~.metrics.MetricsRegistry`,
and that one registry is rendered three ways:

* :mod:`.exposition` — Prometheus text format v0.0.4 over a tiny
  background-thread HTTP endpoint (``/metrics`` + ``/healthz``), the
  scrape surface (``kccap-server -metrics-port``);
* ``registry.snapshot()`` — a JSON-able dict riding the service's
  ``info`` op and ``bench.py``'s artifact;
* :mod:`.tracing` — per-request trace/span IDs threaded through the
  service protocol envelope (the same way ``deadline`` already is), with
  span timings feeding registry histograms and an optional JSONL log.

Hot-path rule: no registry call ever executes inside jitted code.  All
instrumentation lives host-side around kernel dispatch, and the
dispatch-side hooks honor :func:`~.metrics.enabled` so telemetry can be
switched off entirely (``KCCAP_TELEMETRY=0``).
"""

from kubernetesclustercapacity_tpu.telemetry.metrics import (  # noqa: F401
    DEFAULT_LATENCY_BUCKETS_S,
    REGISTRY,
    SUB_MS_LATENCY_BUCKETS_S,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    enabled,
)
from kubernetesclustercapacity_tpu.telemetry.exposition import (  # noqa: F401
    MetricsServer,
    render_text,
    start_metrics_server,
)
from kubernetesclustercapacity_tpu.telemetry.tracing import (  # noqa: F401
    Span,
    TraceLog,
    new_span_id,
    new_trace_id,
)
from kubernetesclustercapacity_tpu.telemetry.flightrec import (  # noqa: F401
    FlightRecorder,
    args_digest,
    result_digest,
)
from kubernetesclustercapacity_tpu.telemetry.compilewatch import (  # noqa: F401
    observe_dispatch,
    seen_kernels,
)
from kubernetesclustercapacity_tpu.telemetry.phases import (  # noqa: F401
    NULL_CLOCK,
    PHASES,
    PhaseClock,
    new_clock,
)

# NOTE: .slo is a deliberate non-export — it rides the timeline/explain
# stack (alert machine, kernels) and must not load on every telemetry
# import; consumers import kubernetesclustercapacity_tpu.telemetry.slo
# directly.
