"""Service boundary: front-end ↔ accelerator-resident capacity service.

The north-star architecture (BASELINE.json) is a thin compiled front-end CLI
talking to a long-lived Python/JAX service that holds the snapshot
device-resident — so interactive queries never pay process startup, JAX
import, or compile time.  This package implements that boundary as a
length-prefixed-JSON protocol over TCP:

* :mod:`.protocol` — framing + request/response schema;
* :mod:`.server`   — threaded TCP server dispatching to the kernels;
* :mod:`.client`   — Python client;
* :mod:`.plane`    — the replicated serving plane: leader→replica
  snapshot pub-sub fan-out, admission control, graceful drain;
* :mod:`.replicaset` — multi-endpoint client: failover, hedged reads,
  read-your-generation monotonicity across replicas;
* ``native/kccap_client.cc`` — the compiled front-end CLI (C++; the
  environment has no Go toolchain or grpcio, so the "Go → gRPC" leg of the
  north-star is realized as "C++ → framed JSON" with identical shape: flag
  parsing in the native front-end, all semantics server-side).
"""

from kubernetesclustercapacity_tpu.service.client import CapacityClient  # noqa: F401
from kubernetesclustercapacity_tpu.service.coalesce import SnapshotCoalescer  # noqa: F401
from kubernetesclustercapacity_tpu.service.replicaset import ReplicaSet  # noqa: F401
from kubernetesclustercapacity_tpu.service.server import CapacityServer  # noqa: F401
