"""Server-side request micro-batching: concurrent sweeps share a kernel.

The dispatch path used to launch one kernel per request even when dozens
of concurrent sweeps targeted the *same* snapshot generation and mode —
each paying its own dispatch overhead for a scenario axis the kernel
would happily evaluate in one launch (the batch-bin-packing observation:
admission queries are tiny; their per-query overhead is the product).

:class:`MicroBatcher` is the continuous-batching analog for the capacity
kernel, leader-driven so it owns no threads:

* the **first** request for a key opens a batch and becomes its leader;
* the leader waits up to ``window_s`` (default ~1–2 ms) while concurrent
  requests for the same key append their scenario rows — a full batch
  (``max_batch``) dispatches early;
* the leader runs ONE combined dispatch on its own thread and scatters
  per-request slices back; followers block on the batch's event and
  return their own slice.

Deadline semantics are preserved per request: a request whose remaining
budget would expire inside the window bypasses batching and dispatches
solo (counted separately), so batching can never *cause* a shed.  Trace
IDs ride the per-request envelope untouched — the batch is invisible on
the wire.

Registry-backed metrics: ``kccap_batch_size`` (batch-size histogram —
``sum/count`` is the mean batch size), ``kccap_batch_window_wait_seconds``
(how long leaders actually waited), ``kccap_batch_tenants`` (distinct
tenants folded into each dispatched batch — cross-tenant folding is the
multi-tenancy win: one padded dispatch, split per tenant on return,
bit-exact vs solo), and batched/solo/bypass counters.
"""

from __future__ import annotations

import threading
import time

__all__ = ["MicroBatcher"]

#: Batch-size buckets: powers of two up to the plausible max_batch range.
_BATCH_SIZE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128)

#: Hard ceiling on a follower's wait for its leader's dispatch: the
#: combined kernel may compile on first dispatch (seconds), but a wedged
#: leader must not strand followers forever.
_FOLLOWER_TIMEOUT_S = 120.0


class _Batch:
    __slots__ = (
        "items", "tenants", "weights", "closed", "full", "done", "results",
        "error", "leader_span_id", "opened_at",
    )

    def __init__(self, opened_at: float = 0.0) -> None:
        self.items: list = []
        # Parallel to ``items``: who asked (None when tenancy is off).
        # Results scatter back BY INDEX, so per-tenant attribution never
        # influences — or could even touch — the combined dispatch.
        self.tenants: list = []
        # Parallel to ``items``: scenario rows each member contributes
        # to the folded dispatch (the fold-accounting weight).
        self.weights: list = []
        # When the leader opened the window (the batcher's clock) — a
        # joiner's bypass decision compares its deadline against the
        # REMAINING window, not the full one.
        self.opened_at = opened_at
        self.closed = False
        self.full = threading.Event()
        self.done = threading.Event()
        self.results: list | None = None
        self.error: str | None = None
        # The leader's "batch:dispatch" span id, minted when the batch
        # opens so followers can LINK to it (links, not parentage:
        # a follower's request is caused by its own caller; it merely
        # rode the leader's dispatch).
        self.leader_span_id: str | None = None


class MicroBatcher:
    """Collect concurrent same-key requests into one dispatch.

    ``dispatch(key, items)`` (the embedder's) must return one result per
    item, in order.  ``key`` groups only requests whose combined dispatch
    is semantically identical to their solo dispatches (the server keys
    by snapshot generation + kernel choice).
    """

    def __init__(
        self,
        dispatch,
        *,
        window_s: float = 0.0015,
        max_batch: int = 32,
        registry=None,
        trace_sink=None,
        fold_hook=None,
        clock=None,
    ) -> None:
        from kubernetesclustercapacity_tpu.telemetry.metrics import (
            MetricsRegistry,
        )

        if window_s <= 0:
            raise ValueError("window_s must be > 0 (omit the batcher to "
                             "disable batching)")
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self._dispatch = dispatch
        # Span sink (a TailSampler or TraceLog; None = no tracing):
        # batch leaders record a "batch:dispatch" span, followers a
        # "batch:join" span linked to it — the trace-tree form of "who
        # rode whose kernel launch".
        self._trace_sink = trace_sink
        # Fold-accounting hook: called once per MULTI-request dispatch
        # with the members' tenant labels (service/tenancy.py's
        # FoldAccounting when tenancy is armed; None otherwise).
        # Strictly best-effort — accounting must never fail a dispatch.
        self._fold_hook = fold_hook
        # Injectable monotonic clock (tests freeze it to pin the
        # joiner-bypass window arithmetic); production uses perf_counter.
        self._clock = clock if clock is not None else time.perf_counter
        self.window_s = float(window_s)
        self.max_batch = int(max_batch)
        self._lock = threading.Lock()
        self._pending: dict = {}
        self.registry = registry if registry is not None else MetricsRegistry()
        m = self.registry
        self._m_size = m.histogram(
            "kccap_batch_size",
            "Requests per dispatched micro-batch (sum/count = mean).",
            buckets=_BATCH_SIZE_BUCKETS,
        )
        self._m_wait = m.histogram(
            "kccap_batch_window_wait_seconds",
            "How long batch leaders waited for followers before "
            "dispatching.",
        )
        self._m_batched = m.counter(
            "kccap_batched_requests_total",
            "Requests served as part of a multi-request batch.",
        )
        self._m_solo = m.counter(
            "kccap_solo_requests_total",
            "Requests dispatched alone (batch of one).",
        )
        self._m_bypass = m.counter(
            "kccap_batch_deadline_bypass_total",
            "Requests that bypassed batching because their deadline "
            "would expire inside the window.",
        )
        self._m_tenants = m.histogram(
            "kccap_batch_tenants",
            "Distinct tenants folded into each dispatched micro-batch "
            "(1 when tenancy is off; >1 means cross-tenant sharing).",
            buckets=_BATCH_SIZE_BUCKETS,
        )
        self._m_specs = m.histogram(
            "kccap_fold_specs",
            "Scenario rows folded into each dispatched micro-batch "
            "(sum of member weights; sum/count = mean folded specs per "
            "launch — the cross-spec amortization factor).",
            buckets=_BATCH_SIZE_BUCKETS + (256, 512, 1024),
        )

    @property
    def stats(self) -> dict:
        """JSON-able batching counters (info op / doctor / bench)."""
        size = self._m_size.labels()
        dispatches = size.count
        total = size.sum
        specs = self._m_specs.labels()
        batched = int(self._m_batched.value)
        solo = int(self._m_solo.value)
        requests = batched + solo
        return {
            "window_ms": self.window_s * 1e3,
            "max_batch": self.max_batch,
            "dispatches": dispatches,
            "batched_requests": batched,
            "solo_requests": solo,
            "deadline_bypass": int(self._m_bypass.value),
            "mean_batch_size": (total / dispatches) if dispatches else 0.0,
            # Fraction of requests that actually shared a launch, and
            # the mean scenario rows per launch — the two numbers the
            # open-loop serving bench row reports.
            "fold_rate": (batched / requests) if requests else 0.0,
            "mean_folded_specs": (
                (specs.sum / specs.count) if specs.count else 0.0
            ),
        }

    def submit(
        self, key, item, *, deadline=None, tenant=None, trace=None, weight=1
    ):
        """Run ``item`` through a (possibly shared) dispatch; returns its
        own result.  Blocking — callers are the server's per-connection
        threads, each already holding a compute slot.

        ``tenant`` is pure attribution: concurrent tenants' same-key
        sweeps FOLD into one padded dispatch and split per tenant on
        return (bit-exact vs solo, because the combined dispatch is
        index-scattered and never reads the label).

        ``weight`` is the scenario-row count this member contributes to
        the folded dispatch (fold accounting only — never consulted by
        the dispatch itself).

        Deadline bypass is per member against the batch it would
        ACTUALLY join: a leader's wait budget is the full window, but a
        joiner's is only the window's remainder — so each member's OWN
        deadline is consulted (never just the leader's), and a joiner
        whose budget would expire before the leader dispatches goes
        solo instead of riding a batch it cannot afford.

        ``trace`` is the caller's
        :class:`~..telemetry.tracectx.TraceContext` (``None`` when the
        request is untraced): the leader's combined dispatch lands as a
        "batch:dispatch" child span of ITS request; every follower
        records a "batch:join" span under its OWN request whose
        ``links`` field names the leader's dispatch span — cross-trace
        causality without fake parentage."""
        solo = False
        with self._lock:
            batch = self._pending.get(key)
            joinable = (
                batch is not None
                and not batch.closed
                and len(batch.items) < self.max_batch
            )
            if deadline is not None:
                # The wait this member would actually sign up for: the
                # whole window when it would open a fresh batch, the
                # REMAINING window when it would join an open one.
                budget = (
                    max(
                        0.0,
                        self.window_s
                        - (self._clock() - batch.opened_at),
                    )
                    if joinable
                    else self.window_s
                )
                if deadline.remaining() <= budget:
                    # The wait would eat the caller's whole budget:
                    # dispatch alone, now.  (An already-expired deadline
                    # was shed upstream.)
                    solo = True
            if not solo:
                leader = False
                if not joinable:
                    batch = _Batch(opened_at=self._clock())
                    if self._trace_sink is not None:
                        from kubernetesclustercapacity_tpu.telemetry.tracing import (  # noqa: E501
                            new_span_id,
                        )

                        batch.leader_span_id = new_span_id()
                    self._pending[key] = batch
                    leader = True
                idx = len(batch.items)
                batch.items.append(item)
                batch.tenants.append(tenant)
                batch.weights.append(weight)
                if len(batch.items) >= self.max_batch:
                    batch.full.set()
        if solo:
            # Outside the lock: a bypass dispatch must never hold the
            # fold queue shut while its kernel runs.
            self._m_bypass.inc()
            self._m_solo.inc()
            self._m_size.observe(1)
            self._m_tenants.observe(1)
            self._m_specs.observe(weight)
            return self._dispatch(key, [item])[0]

        from kubernetesclustercapacity_tpu.telemetry import phases as _phases

        clk = _phases.current()
        if leader:
            t0 = time.perf_counter()
            with clk.live("batch_wait"):
                batch.full.wait(self.window_s)
            with self._lock:
                # Close under the same lock appends take: every item is
                # either in this snapshot or in a successor batch.
                batch.closed = True
                if self._pending.get(key) is batch:
                    del self._pending[key]
                items = list(batch.items)
            waited = time.perf_counter() - t0
            self._m_wait.observe(waited)
            # The leader's batch_wait is the window it held the door
            # open; its combined dispatch below records device phases on
            # this same (request) thread's clock.
            clk.record("batch_wait", waited)
            try:
                results = self._dispatch(key, items)
                if len(results) != len(items):
                    raise RuntimeError(
                        f"batch dispatch returned {len(results)} results "
                        f"for {len(items)} requests"
                    )
                batch.results = results
            except Exception as e:  # noqa: BLE001 - relayed per member
                batch.error = f"{type(e).__name__}: {e}"
                raise
            finally:
                self._m_size.observe(len(items))
                # Distinct tenants per dispatch: None (tenancy off)
                # counts as one anonymous tenant, so the histogram is
                # well-defined on the pre-tenancy path too.
                self._m_tenants.observe(
                    len(set(batch.tenants[: len(items)])) or 1
                )
                self._m_specs.observe(
                    sum(batch.weights[: len(items)]) or 1
                )
                if len(items) > 1:
                    self._m_batched.inc(len(items))
                    if self._fold_hook is not None:
                        try:
                            self._fold_hook(batch.tenants[: len(items)])
                        except Exception:  # noqa: BLE001 - accounting
                            pass  # must never fail a dispatch
                else:
                    self._m_solo.inc()
                batch.done.set()
                if trace is not None and self._trace_sink is not None:
                    from kubernetesclustercapacity_tpu.telemetry import (
                        tracectx as _tracectx,
                    )

                    _tracectx.span(
                        self._trace_sink,
                        ts=time.time(),
                        trace_id=trace.trace_id,
                        span_id=batch.leader_span_id,
                        parent_span_id=trace.span_id,
                        op="batch:dispatch",
                        service="server",
                        leader=True,
                        batch_size=len(items),
                        duration_ms=round(
                            (time.perf_counter() - t0) * 1e3, 3
                        ),
                        status="error" if batch.error else "ok",
                    )
        else:
            t0 = time.perf_counter()
            with clk.live("batch_wait"):
                done = batch.done.wait(_FOLLOWER_TIMEOUT_S)
            wait_s = time.perf_counter() - t0
            # A follower's whole batching story is this wait: the
            # remainder of the leader's window plus the combined kernel
            # dispatch it rode.  Its own clock never sees device phases
            # — the leader's does — so batch_wait is the honest
            # per-request attribution.
            if clk:
                clk.record("batch_wait", wait_s)
            if trace is not None and self._trace_sink is not None:
                from kubernetesclustercapacity_tpu.telemetry import (
                    tracectx as _tracectx,
                )
                from kubernetesclustercapacity_tpu.telemetry.tracing import (
                    new_span_id,
                )

                _tracectx.span(
                    self._trace_sink,
                    ts=time.time(),
                    trace_id=trace.trace_id,
                    span_id=new_span_id(),
                    parent_span_id=trace.span_id,
                    op="batch:join",
                    service="server",
                    leader=False,
                    **(
                        {"links": [batch.leader_span_id]}
                        if batch.leader_span_id
                        else {}
                    ),
                    duration_ms=round(wait_s * 1e3, 3),
                    status="ok" if done else "error",
                )
            if not done:
                raise RuntimeError(
                    "micro-batch dispatch timed out waiting for its leader"
                )
        if batch.error is not None:
            raise RuntimeError(f"batched dispatch failed: {batch.error}")
        return batch.results[idx]
