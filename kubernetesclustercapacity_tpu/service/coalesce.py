"""Coalesce watch-event bursts into bounded snapshot pushes.

The live-serve wiring (``server.main -follow``) turns every applied watch
event into a full snapshot repack+swap — O(N) array materialization under
the store lock.  At 10k nodes with routine churn (kubelet status updates
arrive per node, per sync period) that made the repack the hot path: the
reference's analog failure is its per-run ``1 + 2N + ΣP`` apiserver walk
(SURVEY.md §3.4) — work proportional to cluster size on every freshness
tick.

:class:`SnapshotCoalescer` decouples event application (cheap per-row
store upserts, already O(1)) from snapshot publication (O(N) repack):

* **leading edge** — the first event after an idle period flushes
  immediately (an isolated change is visible at once);
* **suppression window** — further events within ``min_interval_s``
  accumulate; at window end one trailing flush publishes the final state;
* **backlog bound** — if pending events reach ``max_pending`` before the
  window ends, flush early (a huge relist-scale burst is not held back
  for the full window);
* **no lost finale** — :meth:`stop` drains: the last pending state is
  always flushed before the worker exits.

Because ``flush`` runs on the coalescer's own worker thread, the serve
wiring also uses it to PRE-WARM the device cache for the just-published
snapshot (``server.main`` passes ``warm=True`` to ``replace_snapshot``
inside the flush callback): the O(N) host→device upload for the next
generation is paid here, off the request path, so a relist never stalls
a reader on a cold cache.

So a churn storm of E events costs ``min(E, 2 + duration/min_interval_s
+ E/max_pending)`` repacks instead of E, while staleness stays bounded by
``min_interval_s``.
"""

from __future__ import annotations

import threading

from kubernetesclustercapacity_tpu.utils.threads import supervised
import time

__all__ = ["SnapshotCoalescer"]


class SnapshotCoalescer:
    """Run ``flush()`` at a bounded rate in response to ``notify()`` bursts.

    ``flush`` runs on the coalescer's own worker thread (never on the
    notifier's — watch threads must not pay repack latency).  A raising
    ``flush`` is recorded in :attr:`last_error` and reported to
    ``on_error`` (if given); the worker itself keeps running — the
    EMBEDDER decides whether a failed publish is fatal.  A supervised
    server must treat it as such (see ``server.main``): before
    coalescing, a publish failure killed the watch thread and the serve
    loop with it; silently serving a frozen snapshot is the one
    unacceptable outcome.
    """

    def __init__(
        self,
        flush,
        *,
        min_interval_s: float = 0.1,
        max_pending: int = 256,
        on_error=None,
    ) -> None:
        if min_interval_s < 0:
            raise ValueError("min_interval_s must be >= 0")
        if max_pending < 1:
            raise ValueError("max_pending must be >= 1")
        self._flush = flush
        self._on_error = on_error
        self._min_interval = float(min_interval_s)
        self._max_pending = int(max_pending)
        self._cv = threading.Condition()
        self._pending = 0
        self._stopping = False
        self.events = 0  # total notify() calls
        self.flushes = 0  # total flush() completions
        self.last_error: str | None = None
        # Publish freshness evidence (read by /healthz via stats()):
        # when the last flush finished and how long it took — the
        # coalescer-side witness that publishes (and whatever rides
        # them: cache warming, timeline observation) are still flowing.
        self.last_flush_ts: float | None = None
        self.last_flush_s: float | None = None
        self._thread = threading.Thread(
            target=supervised(self._run, name="kccap-coalescer"),
            daemon=True,
        )
        self._thread.start()

    def stats(self) -> dict:
        """JSON-able counters + freshness (no lock: single-writer fields
        read for display only)."""
        return {
            # kccap: lint-ok[lock-discipline] single-writer counter, torn display read is acceptable
            "events": self.events,
            "flushes": self.flushes,
            # kccap: lint-ok[lock-discipline] single-writer gauge, display-only read
            "pending": self._pending,
            "last_error": self.last_error,
            "last_flush_s": self.last_flush_s,
            "last_flush_age_s": (
                None
                if self.last_flush_ts is None
                else round(time.monotonic() - self.last_flush_ts, 3)
            ),
        }

    def notify(self, *_args, **_kw) -> None:
        """Signal one applied event.  Signature-compatible with the
        follower's ``on_event(kind, etype, obj)`` so it can be installed
        directly as the observer."""
        with self._cv:
            if self._stopping:
                return
            self._pending += 1
            self.events += 1
            self._cv.notify()

    def stop(self, timeout: float | None = 10.0) -> bool:
        """Drain (flush any pending state) and stop the worker.

        Returns True when the worker exited (drain complete).  A False
        return means the drain timed out — a wedged flush callback — and
        the final pending state may never publish; that broken contract
        is recorded in :attr:`last_error` and reported to ``on_error``
        exactly like a raising flush, so a supervised server treats it
        as the publish failure it is.
        """
        with self._cv:
            self._stopping = True
            self._cv.notify()
        self._thread.join(timeout)
        if self._thread.is_alive():
            err = (
                f"coalescer drain timed out after {timeout}s "
                "(flush callback wedged); final state may be unpublished"
            )
            self.last_error = err
            if self._on_error is not None:
                try:
                    self._on_error(err)
                except Exception:  # noqa: BLE001 - observer must not kill us
                    pass
            return False
        return True

    # -- worker ------------------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._stopping and self._pending == 0:
                    self._cv.wait()
                if self._pending == 0:  # stopping with nothing to drain
                    return
                self._pending = 0
            self._do_flush()
            # Suppression window: absorb the burst.  Wake early only for
            # stop (drain) or a backlog at max_pending.
            deadline = time.monotonic() + self._min_interval
            with self._cv:
                while (
                    not self._stopping
                    and self._pending < self._max_pending
                ):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)

    def _do_flush(self) -> None:
        t0 = time.monotonic()
        try:
            self._flush()
        except Exception as e:  # noqa: BLE001 - embedder decides fatality
            self.last_error = f"{type(e).__name__}: {e}"
            if self._on_error is not None:
                try:
                    self._on_error(self.last_error)
                except Exception:  # noqa: BLE001 - observer must not kill us
                    pass
        else:
            self.flushes += 1
            self.last_flush_ts = time.monotonic()
            self.last_flush_s = round(self.last_flush_ts - t0, 6)
